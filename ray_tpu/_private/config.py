"""Central runtime configuration with environment overrides.

Equivalent of the reference's RAY_CONFIG macro table (reference:
src/ray/common/ray_config_def.h:18-22 — 219 typed flags, each
overridable via `RAY_<name>` env vars or a `_system_config` dict passed
at init). We keep the same contract: every flag is typed, has a
default, can be overridden by `RT_<name>` in the environment or by the
`_system_config` dict handed to `ray_tpu.init`.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields
from typing import Any

_ENV_PREFIX = "RT_"


@dataclass
class Config:
    # ---- transport (reference: gRPC over DCN; node_manager_port etc.
    # in ray_config_def.h / services.py) ----
    #: When set, every daemon additionally binds a TCP listener on this
    #: host (port ephemeral unless node_listen_port is set) and
    #: advertises tcp://host:port cluster-wide instead of its Unix
    #: socket — required for real multi-host deployments.
    node_listen_host: str = ""
    #: Fixed TCP port for the daemon listener (0 = ephemeral).
    node_listen_port: int = 0

    # ---- object store ----
    #: Objects at or below this size are passed inline in task
    #: specs/replies instead of the shared-memory store (reference:
    #: max_direct_call_object_size, ray_config_def.h).
    max_direct_call_object_size: int = 100 * 1024
    #: Shared-memory store capacity per node (bytes). 0 = auto (30% of
    #: system memory, like the reference's default_object_store_memory).
    object_store_memory: int = 0
    #: Chunk size for cross-node object transfer (reference:
    #: object_manager_default_chunk_size = 5 MiB, ray_config_def.h:341).
    object_transfer_chunk_size: int = 5 * 1024 * 1024
    #: Max bytes in flight for object pulls per node.
    object_pull_max_bytes_in_flight: int = 256 * 1024 * 1024
    #: Seconds between object-store eviction scans.
    object_eviction_check_interval_s: float = 1.0
    #: Spill sealed objects to session-dir files under store pressure
    #: and restore them on get (reference: local_object_manager.h:110
    #: SpillObjectsOfSize over external_storage.py FileSystemStorage).
    object_spilling_enabled: bool = True
    #: Store-usage fraction above which the daemon spills LRU sealed
    #: objects to disk (reference: object_spilling_threshold = 0.8,
    #: ray_config_def.h).
    object_spilling_threshold: float = 0.8
    #: Use the native C++ arena store (_native/store.cc) instead of
    #: per-object Python shm segments. Reader safety is plasma-style:
    #: atomic pin+view on get, pin-deferred deletion, and dead-reader
    #: pin reaping (see NativeArenaStore). Default ON: one mmap'd
    #: arena beats per-object segments on create/open cost and gives
    #: zero-copy reads (plasma equivalence, r2 verdict weak #4).
    use_native_object_store: bool = True

    # ---- memory monitor (reference: memory_monitor.h:52, threshold
    # ray_config_def.h:65 memory_usage_threshold) ----
    #: Node memory fraction beyond which the OOM killer picks a worker.
    memory_usage_threshold: float = 0.95
    #: Sample interval in ms; 0 disables the monitor (default: opt-in,
    #: the hermetic test environment shares the host with other jobs).
    memory_monitor_refresh_ms: int = 0

    # ---- scheduler ----
    #: Beyond this fraction of node utilization the hybrid policy
    #: spreads instead of packing (reference:
    #: scheduler_spread_threshold, hybrid_scheduling_policy.h).
    scheduler_spread_threshold: float = 0.5
    #: Top-k fraction of nodes considered for random placement.
    scheduler_top_k_fraction: float = 0.2
    #: Max worker processes kept warm per node. 0 = num_cpus.
    worker_pool_max_idle_workers: int = 2
    #: Worker processes spawned at daemon start so the first task
    #: skips the ~0.2s cold spawn (reference: WorkerPool prestart,
    #: worker_pool.cc PrestartWorkers / RAY_prestart_worker_first_driver).
    worker_prestart_count: int = 1
    #: Seconds an idle leased worker is kept before being returned.
    worker_lease_idle_timeout_s: float = 1.0
    #: Direct task transport: drivers lease workers and push task specs
    #: straight to them, results inline in the reply (reference:
    #: normal_task_submitter.cc direct calls). Daemon keeps placement.
    use_direct_calls: bool = True
    #: Max concurrently leased workers per scheduling key per driver —
    #: an anti-runaway bound only; the daemon scheduler's resource
    #: admission is the real concurrency gate, so this must stay above
    #: any concurrency the declared resources can admit.
    direct_call_max_leases: int = 64

    # ---- batched task submission (reference: the CoreWorker submit
    # path amortizes the raylet round trip; here one wire round trip
    # covers a whole spec batch) ----
    #: Kill switch: False reverts every submit path to per-task RPCs
    #: (`submit_task` / `execute_task`), the pre-batching wire shape.
    task_submit_batching: bool = True
    #: Max specs coalesced into one `submit_tasks` / `execute_tasks`
    #: frame. Batches form only under backlog — an idle pipeline sends
    #: a single-spec frame immediately, so latency never waits on a
    #: flush timer (flush interval is effectively 0).
    submit_batch_max_specs: int = 256
    #: Bounded in-flight window: max specs outstanding per leased
    #: worker connection (direct path) before further submissions
    #: queue driver-side — the backpressure that keeps a 1M-task
    #: flood out of the wire while the queue absorbs it.
    submit_inflight_specs: int = 512
    #: In-flight `submit_tasks` batches per driver on the daemon path
    #: before the submit queue holds further frames back.
    submit_inflight_batches: int = 4
    #: Cap on the TASK worker pool per node (0 = 4 * num_cpus).
    #: Actor-dedicated workers are exempt — one per live actor,
    #: admission-controlled by the actor's resource request — so total
    #: processes on an actor-heavy node can exceed this.
    max_workers_per_node: int = 0
    #: Spawn workers by forking a warm pre-imported template process
    #: (~10ms/worker) instead of cold `python -m` (~250ms/worker).
    worker_fork_server: bool = True

    # ---- cluster ----
    #: Seconds between node load-report heartbeats to the head
    #: (reference: ray_syncer resource broadcast period).
    heartbeat_interval_s: float = 0.25

    # ---- fault tolerance ----
    #: Persist head control-plane tables (KV, jobs, nodes, actors) to
    #: an op log in the session dir; a head restarted over the same
    #: session replays it and worker nodes resync (reference: GCS over
    #: a Redis store client + HandleNotifyGCSRestart resync).
    gcs_fault_tolerance: bool = True
    #: Default max retries for tasks (reference: task default 3).
    task_max_retries: int = 3
    #: Default max restarts for actors.
    actor_max_restarts: int = 0
    #: Period of node health probes from the control plane (reference:
    #: gcs_health_check_manager.h period/threshold).
    health_check_period_s: float = 1.0
    #: Consecutive failed probes before a node is declared dead.
    health_check_failure_threshold: int = 5
    #: RPC retry backoff base/cap in seconds.
    rpc_retry_base_s: float = 0.1
    rpc_retry_max_s: float = 2.0

    # ---- log streaming (reference: _private/log_monitor.py tails
    # worker logs and publishes them; the driver prints them with
    # worker prefixes, worker.py:1966 print_to_stdstream) ----
    #: Stream worker stdout/stderr lines to connected drivers.
    log_to_driver: bool = True
    #: Seconds between log-file tail scans.
    log_monitor_interval_s: float = 0.2

    # ---- task events / observability ----
    #: Ring-buffer length of task state events kept by the control
    #: plane (reference: GcsTaskManager).
    task_events_max_buffer: int = 10000
    #: Whether workers batch task state events to the control plane.
    task_events_enabled: bool = True
    #: Always-on per-process flight recorder (_private/flight_recorder
    #: .py): RPC latencies, task begin/end, store put/get, lock waits
    #: in a bounded ring, pulled lazily by the head / `ray_tpu doctor`.
    flight_recorder_enabled: bool = True
    #: Ring capacity (records) of each process's flight recorder.
    flight_recorder_capacity: int = 4096
    #: `rt.diagnose()` defaults: a task with no state transition for
    #: this many seconds counts as hung; a worker whose median step
    #: time exceeds the cluster p50 by this factor is a straggler.
    doctor_hung_task_s: float = 60.0
    doctor_straggler_threshold: float = 1.5
    #: Seconds between head metric-table snapshots appended to the
    #: bounded time-series ring (`/api/timeseries`); 0 disables the
    #: snapshot loop (kill switch: RT_metrics_timeseries_interval_s=0,
    #: the history analog of RT_flight_recorder_enabled).
    metrics_timeseries_interval_s: float = 5.0
    #: Snapshots retained in the head time-series ring (oldest evict
    #: first; 720 x 5 s = a one-hour window by default).
    metrics_timeseries_max_snapshots: int = 720
    #: Seconds between per-node memory-report folds into the head's
    #: memory ledger (object attribution, per-job usage, doctor
    #: verdict.memory); 0 disables the ledger WHOLE — report loops,
    #: on-demand head folds, chip·s accounting, the rt_job_* /
    #: rt_object_owner_* series, and verdict.memory all stand down
    #: (`ray_tpu memory` says so). Off-path like the time-series
    #: snapshots: the fold reads the object table once per tick,
    #: never per seal/get.
    memory_report_interval_s: float = 5.0
    #: Largest live objects carried per node memory report (the
    #: `ray_tpu memory` top-objects table; bounds report size).
    memory_report_topk: int = 20
    #: `verdict.memory` leak deadline: an object still held this many
    #: seconds after its creation whose owner process died (or whose
    #: job ended) is named a leak suspect.
    doctor_leak_age_s: float = 300.0
    #: Data-plane provenance reporting (ISSUE 20): each worker
    #: classifies every rt.get resolution (inline / local / pull /
    #: restore_local / restore_remote), aggregates per (provenance,
    #: src node, task class), and drains the aggregates onto the
    #: metrics pipe at most once per this interval (riding the pipe's
    #: flush tick — batched like step records, NEVER one RPC per get);
    #: daemons report pull/restore transfer records the same way. The
    #: head folds both into the memory ledger's transfer matrix
    #: (`transfer_summary`, /api/transfers, `ray_tpu memory
    #: --transfers`, rt_object_transfer_* series). 0 disables the
    #: whole data-plane instrument (kill switch: workers record
    #: nothing, daemons report nothing — the flight-recorder
    #: contract).
    transfer_report_interval_s: float = 0.5
    #: `verdict.data` misplacement conviction bar: a task class whose
    #: gets pulled at least this FRACTION of their bytes from remote
    #: nodes (and at least 1 MB absolute) while a copy-holding node
    #: had capacity is named a misplaced-task suspect. Raise it to
    #: quiet the verdict on broadcast-heavy workloads whose pulls are
    #: inherent, not placement error.
    doctor_locality_miss_threshold: float = 0.5
    #: Runtime lock-order witness (devtools/lock_witness.py): wraps
    #: the hot-path locks created through `make_lock` so the process
    #: records its ACTUAL lock-acquisition-order graph plus
    #: held-while-blocking events into the flight recorder, cycle-
    #: checked at exit and by `rt.diagnose()` (verdict.locks). Off by
    #: default — enable with RT_lock_witness_enabled=1 in the
    #: environment BEFORE the cluster starts so daemons and workers
    #: (which inherit the env) wrap their locks from birth; when off,
    #: `make_lock` returns raw threading locks (zero overhead — the
    #: wrapper is not installed, there is no runtime branch).
    lock_witness_enabled: bool = False
    #: Cap on distinct lock-order edges the witness tracks per
    #: process; first-seen edges keep their acquisition stacks,
    #: overflow increments a dropped counter in the snapshot.
    lock_witness_max_edges: int = 4096
    #: XLA compile watcher (_private/compile_watch.py): per-process
    #: listener recording every compilation of a registered jitted
    #: program as (name, shape digest, duration) — compile counters
    #: on /metrics, compile_ms as a step stall phase, recompile-storm
    #: detection in `doctor`. Env RT_compile_watch_enabled=0 is the
    #: per-process kill switch (flight-recorder contract).
    compile_watch_enabled: bool = True
    #: Distinct shape digests of ONE program past which the doctor
    #: calls a recompile storm (`verdict.compile`). Set above any
    #: legitimate bucket family (prefill length buckets, policy batch
    #: buckets top out at ~6) so healthy bucketed programs never trip
    #: it while a drifting shape — one new digest per iteration —
    #: crosses it within seconds.
    compile_storm_threshold: int = 8
    #: Cap on one coordinated gang-profile window
    #: (`rt.profile_gang` / `ray_tpu profile --job`): every rank
    #: samples for the whole window and the head holds one RPC pool
    #: thread per rank for it.
    profile_gang_max_duration_s: float = 60.0
    #: Kill switch for the continuous-batching LLM serving engine
    #: (ray_tpu/llm): RT_serve_engine_enabled=0 makes `build_llm_app`
    #: deployments fall back to per-request `generate_stream()` — the
    #: serialize-per-request baseline servebench.py compares against.
    serve_engine_enabled: bool = True
    #: Kill switch for paged-KV prefix caching (ray_tpu/llm/kv_slots):
    #: RT_serve_prefix_cache_enabled=0 makes every `build_llm_app`
    #: engine prefill every prompt from scratch (blocks stay private,
    #: nothing registers in the prefix table). Resolved driver-side by
    #: build_llm_app, like serve_engine_enabled.
    serve_prefix_cache_enabled: bool = True
    #: Serve request routing policy (serve/router.py):
    #: "least_tokens" routes each request to the candidate replica
    #: with the fewest estimated outstanding tokens (prompt + token
    #: budget, decremented as chunks stream back); "pow2" restores the
    #: PR-era power-of-two-choices on in-flight request counts.
    serve_routing_policy: str = "least_tokens"
    #: SLO admission control (kill switch
    #: RT_serve_slo_admission_enabled=0): when even the LEAST-loaded
    #: candidate replica's estimated outstanding tokens exceed
    #: serve_slo_queue_threshold_tokens, the router raises
    #: DeploymentOverloaded and the proxy sheds the request with
    #: 503 + Retry-After instead of queueing it into TTFT collapse.
    serve_slo_admission_enabled: bool = True
    #: Outstanding-token threshold per replica for SLO shedding — an
    #: estimate of the replica's engine queue depth in tokens (at the
    #: full-path token rate this bounds worst-case time-to-first-token
    #: for admitted requests).
    serve_slo_queue_threshold_tokens: int = 1024
    #: MPMD pipeline training (train/mpmd_pipeline.py): records a
    #: channel edge buffers before put() blocks the producer — the
    #: pipeline's backpressure bound (channel capacity = depth x
    #: microbatch-activation record size). 1F1B needs only ~2 in
    #: flight per edge in steady state; extra depth absorbs stage
    #: jitter without letting a fast stage run unboundedly ahead.
    pipeline_channel_depth: int = 4
    #: Per-hop channel put/get timeout inside a pipeline stage. A
    #: stage blocked longer than this fails the step (the driver
    #: additionally closes all edges on ANY stage failure so peers
    #: unblock immediately rather than waiting this out).
    pipeline_hop_timeout_s: float = 120.0
    #: End-to-end bound on one MPMDPipeline.step(): the driver aborts
    #: (closing every edge) and raises rather than hang past it.
    pipeline_step_timeout_s: float = 600.0

    # ---- decoupled RL dataflow (rl/dataflow.py, ISSUE 13) ----
    #: Rollout-queue capacity in FRAGMENTS: past it, env-runner puts
    #: are refused ("full") and runners wait — the backpressure that
    #: throttles actors when the learner falls behind instead of
    #: growing an unbounded staleness backlog.
    rl_rollout_queue_capacity: int = 16
    #: Bound on off-policy staleness in weight VERSIONS: a fragment
    #: generated more than this many published learner versions ago
    #: is refused at put ("throttle": the runner refreshes weights
    #: first) and dropped at get if it aged out while queued. 0 =
    #: strictly on-policy-by-version.
    rl_max_weight_lag: int = 4
    #: Publish learner weights (drainless engine push + weight-store
    #: publish) every N learner updates. 1 = every update, the
    #: synchronous path's freshness at none of its blocking.
    rl_weight_sync_interval_updates: int = 1

    # ---- testing / chaos ----
    #: Fault-injection spec "method=count" — drop the first `count`
    #: RPCs with the given method name (reference: rpc_chaos.h:23-31,
    #: env RAY_testing_rpc_failure).
    testing_rpc_failure: str = ""

    @classmethod
    def from_env(cls, overrides: dict[str, Any] | None = None) -> "Config":
        cfg = cls()
        for f in fields(cls):
            env_key = _ENV_PREFIX + f.name
            if env_key in os.environ:
                setattr(cfg, f.name, _parse(f.type, os.environ[env_key]))
        for key, value in (overrides or {}).items():
            if not hasattr(cfg, key):
                raise ValueError(f"Unknown config flag: {key}")
            setattr(cfg, key, value)
        return cfg

    def to_dict(self) -> dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


def _parse(type_name: str, raw: str) -> Any:
    if type_name in ("int",):
        return int(raw)
    if type_name in ("float",):
        return float(raw)
    if type_name in ("bool",):
        return raw.lower() in ("1", "true", "yes")
    if type_name in ("str",):
        return raw
    return json.loads(raw)
