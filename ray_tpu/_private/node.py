"""Session bootstrap: start/stop node processes.

Mirrors the reference's Node/services layer (reference:
python/ray/_private/node.py:37, services.py:829 — spawns GCS, raylet,
dashboard, log monitor). Here a head "session" embeds the NodeDaemon
(raylet+GCS) in the driver process behind its Unix socket, so a bare
`init()` needs no separate binaries; `init(address=...)` instead
connects to a daemon started by `rt start --head` (cli.py).
"""

from __future__ import annotations

import atexit
import glob
import os
import tempfile
import time
from typing import Dict, Optional

from .config import Config
from .daemon import NodeDaemon
from .rpc import configure_chaos
from .worker import CoreWorker, set_global_worker


def detect_num_tpu_chips() -> int:
    """TPU chip count via device files (reference:
    python/ray/_private/accelerators/tpu.py:107 — counts /dev/accel*)."""
    chips = len(glob.glob("/dev/accel*"))
    if chips:
        return chips
    if glob.glob("/dev/vfio/*"):
        return len([p for p in glob.glob("/dev/vfio/*") if p.split("/")[-1].isdigit()])
    return 0


class Session:
    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        system_config: Optional[dict] = None,
        address: Optional[str] = None,
        session_dir: Optional[str] = None,
    ):
        self.config = Config.from_env(system_config)
        if self.config.testing_rpc_failure:
            configure_chaos(self.config.testing_rpc_failure)
        self.daemon: Optional[NodeDaemon] = None
        if address is None:
            self.session_dir = session_dir or tempfile.mkdtemp(
                prefix=f"rt_session_{int(time.time())}_"
            )
            total = dict(resources or {})
            total.setdefault(
                "CPU", float(num_cpus if num_cpus is not None else os.cpu_count())
            )
            tpus = (
                float(num_tpus)
                if num_tpus is not None
                else float(detect_num_tpu_chips())
            )
            if tpus:
                total.setdefault("TPU", tpus)
            total.setdefault("memory", float(2**34))
            self.daemon = NodeDaemon(
                self.session_dir, total, self.config, is_head=True
            )
            self.daemon.start()
            address = self.daemon.socket_path
        self.address = address
        self.worker = CoreWorker(address, role="driver")
        set_global_worker(self.worker)
        atexit.register(self.shutdown)

    def shutdown(self) -> None:
        try:
            atexit.unregister(self.shutdown)
        except Exception:
            pass
        set_global_worker(None)
        if self.worker is not None:
            self.worker.shutdown()
            self.worker = None
        if self.daemon is not None:
            self.daemon.shutdown()
            self.daemon = None
