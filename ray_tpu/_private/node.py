"""Session bootstrap: start/stop node processes.

Mirrors the reference's Node/services layer (reference:
python/ray/_private/node.py:37, services.py:829 — spawns GCS, raylet,
dashboard, log monitor). Here a head "session" embeds the NodeDaemon
(raylet+GCS) in the driver process behind its Unix socket, so a bare
`init()` needs no separate binaries; `init(address=...)` instead
connects to a daemon started by `rt start --head` (cli.py).
"""

from __future__ import annotations

import atexit
import os
import tempfile
import time
from typing import Dict, Optional

from .accelerators import detect_accelerators
from .config import Config
from .daemon import NodeDaemon
from .rpc import configure_chaos
from .worker import CoreWorker, set_global_worker


class Session:
    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        system_config: Optional[dict] = None,
        address: Optional[str] = None,
        session_dir: Optional[str] = None,
    ):
        self.config = Config.from_env(system_config)
        if self.config.testing_rpc_failure:
            configure_chaos(self.config.testing_rpc_failure)
        self.daemon: Optional[NodeDaemon] = None
        if address is None:
            self.session_dir = session_dir or tempfile.mkdtemp(
                prefix=f"rt_session_{int(time.time())}_"
            )
            total = dict(resources or {})
            total.setdefault(
                "CPU", float(num_cpus if num_cpus is not None else os.cpu_count())
            )
            detected, labels = detect_accelerators(
                {"TPU": float(num_tpus)} if num_tpus is not None else None
            )
            for name, amount in detected.items():
                if amount:
                    total.setdefault(name, amount)
            total.setdefault("memory", float(2**34))
            self.daemon = NodeDaemon(
                self.session_dir,
                total,
                self.config,
                is_head=True,
                labels=labels,
            )
            self.daemon.start()
            address = self.daemon.socket_path  # driver rides the local Unix socket
        self.address = address
        self.worker = CoreWorker(address, role="driver")
        set_global_worker(self.worker)
        atexit.register(self.shutdown)

    def shutdown(self) -> None:
        try:
            atexit.unregister(self.shutdown)
        except Exception:
            pass
        set_global_worker(None)
        # Worker teardown can fail under extreme conditions (observed:
        # thread creation raising on a pid-exhausted host) — the
        # daemon, which owns the spawned worker TREE, must still be
        # torn down or orphaned workers outlive the session.
        try:
            if self.worker is not None:
                self.worker.shutdown()
        finally:
            self.worker = None
            if self.daemon is not None:
                daemon, self.daemon = self.daemon, None  # rt: noqa[RT201] — atexit.unregister above runs before teardown: the finalizer and a live caller never overlap
                daemon.shutdown()
