"""Per-node daemon: local scheduler + worker pool + object directory,
with the control plane embedded on the head node.

This process plays the role of the reference's raylet (reference:
src/ray/raylet/node_manager.h — worker leasing node_manager.cc:1807,
dependency-gated dispatch local_task_manager.cc:122, worker pool
worker_pool.cc:1312) and, on the head node, also the GCS server
(src/ray/gcs/gcs_server/gcs_server.h). Folding GCS into the head
daemon replaces the reference's separate `gcs_server` binary; the
tables are the same (`gcs.ControlState`).

Workers and drivers connect over a Unix socket (`rpc.RpcServer`).
Large objects never pass through this process: clients write them
straight into per-object shared memory and only the seal notification
flows here (the plasma create/seal protocol,
src/ray/object_manager/plasma/store.h).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .config import Config
from .gcs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ACTOR_PENDING_CREATION,
    ACTOR_RESTARTING,
    ActorInfo,
    ControlState,
    JobInfo,
    NodeInfo,
)
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from .object_store import SharedMemoryStore
from .rpc import DEFERRED, Connection, RpcServer
from .scheduler import LocalScheduler, ResourceSet

# Object entry states.
PENDING = "PENDING"
SEALED = "SEALED"
ERRORED = "ERRORED"


@dataclass
class ObjectEntry:
    state: str = PENDING
    size: int = 0
    inline: Optional[bytes] = None  # small objects live here
    error: Optional[bytes] = None  # serialized TaskError payload
    in_shm: bool = False
    refcount: int = 1
    waiters: List[tuple] = field(default_factory=list)  # (conn, mid)


@dataclass
class WorkerInfo:
    conn: Connection
    worker_id: WorkerID
    pid: int
    idle: bool = True
    is_tpu: bool = False
    pinned_actor: Optional[ActorID] = None
    current_task: Optional[TaskID] = None


@dataclass
class TaskEntry:
    spec: dict
    state: str = "PENDING"
    retries_left: int = 0


@dataclass
class ActorRuntime:
    creation_spec: dict
    info: ActorInfo
    worker_conn_id: Optional[int] = None
    pending: deque = field(default_factory=deque)  # specs awaiting ALIVE
    # Specs pushed to the actor's worker and not yet completed; failed
    # as a group if the worker dies (reference: ActorTaskSubmitter
    # resends/fails unacked tasks on death).
    inflight: Dict[TaskID, dict] = field(default_factory=dict)
    # Creation args stay pinned for the actor's restartable lifetime
    # (restarts re-dispatch creation_spec); unpinned exactly once on
    # permanent death (reference: lineage pinning keeps the creation
    # task's args reachable while the actor may restart).
    creation_unpinned: bool = False


class NodeDaemon:
    def __init__(
        self,
        session_dir: str,
        resources: Dict[str, float],
        config: Config,
        is_head: bool = True,
    ):
        self.session_dir = session_dir
        self.config = config
        self.node_id = NodeID.from_random()
        self.socket_path = os.path.join(session_dir, "hostd.sock")
        os.makedirs(session_dir, exist_ok=True)

        capacity = config.object_store_memory or _default_store_bytes()
        self.store = SharedMemoryStore(self.node_id.hex(), capacity)
        self.control = ControlState(config.task_events_max_buffer)
        self.scheduler = LocalScheduler(ResourceSet(resources))
        self.resources = dict(resources)

        self._lock = threading.RLock()
        self.objects: Dict[ObjectID, ObjectEntry] = {}
        self.tasks: Dict[TaskID, TaskEntry] = {}
        self.actors: Dict[ActorID, ActorRuntime] = {}
        self.workers: Dict[int, WorkerInfo] = {}  # conn_id -> info
        self.drivers: Dict[int, JobID] = {}  # conn_id -> job
        self._spawning = 0
        self._spawn_failures = 0
        self._shutdown = False
        self._worker_procs: List[subprocess.Popen] = []

        max_workers = config.max_workers_per_node or max(
            4, int(4 * resources.get("CPU", 1))
        )
        self._max_workers = max_workers

        self.control.register_node(
            NodeInfo(
                node_id=self.node_id,
                address=self.socket_path,
                resources=dict(resources),
                is_head=is_head,
            )
        )

        self.server = RpcServer(self.socket_path)
        for name in [
            "register_client",
            "kv_put",
            "kv_get",
            "kv_keys",
            "submit_task",
            "submit_actor_task",
            "create_actor",
            "get_object",
            "wait_objects",
            "put_inline",
            "object_sealed",
            "seal_error",
            "task_done",
            "del_ref",
            "add_ref",
            "get_named_actor",
            "get_actor_info",
            "kill_actor",
            "cancel_task",
            "cluster_resources",
            "available_resources",
            "state_summary",
            "list_task_events",
            "list_nodes",
            "list_actors",
            "ping",
        ]:
            self.server.register(name, getattr(self, "_h_" + name))
        self.server.register("_disconnect", self._h_disconnect)

    def start(self) -> None:
        self.server.start()

    # ------------------------------------------------------------------
    # registration / lifecycle
    # ------------------------------------------------------------------
    def _h_register_client(self, conn: Connection, msg: dict):
        role = msg["role"]
        if role == "worker":
            info = WorkerInfo(
                conn=conn,
                worker_id=WorkerID.from_random(),
                pid=msg["pid"],
                is_tpu=bool(msg.get("is_tpu", False)),
            )
            with self._lock:
                self.workers[conn.conn_id] = info
                self._spawning = max(0, self._spawning - 1)
                self._spawn_failures = 0
            conn.metadata["role"] = "worker"
            self._schedule()
            return {
                "node_id": self.node_id.binary(),
                "worker_id": info.worker_id.binary(),
                "store_capacity": self.store.size_info()["capacity"],
                "config": self.config.to_dict(),
            }
        # driver
        job_id = self.control.next_job_id()
        self.control.add_job(
            JobInfo(
                job_id=job_id,
                driver_pid=msg["pid"],
                start_time=time.time(),
                entrypoint=msg.get("entrypoint", ""),
            )
        )
        with self._lock:
            self.drivers[conn.conn_id] = job_id
        conn.metadata["role"] = "driver"
        return {
            "node_id": self.node_id.binary(),
            "job_id": job_id.binary(),
            "store_capacity": self.store.size_info()["capacity"],
            "config": self.config.to_dict(),
        }

    def _h_disconnect(self, conn: Connection, msg: dict):
        with self._lock:
            winfo = self.workers.pop(conn.conn_id, None)
            self.drivers.pop(conn.conn_id, None)
        if winfo is None:
            return {}
        # Worker died (reference: raylet detects worker death via the
        # socket, node_manager.cc:1089 publishes WorkerDeltaData).
        if winfo.pinned_actor is not None:
            self._on_actor_worker_death(winfo)
        elif winfo.current_task is not None:
            self._on_task_worker_death(winfo)
        return {}

    def _h_ping(self, conn, msg):
        return {"ok": True, "node_id": self.node_id.binary()}

    # ------------------------------------------------------------------
    # KV (function/actor-class blobs — reference: GcsKvManager +
    # function_manager.py export/fetch protocol)
    # ------------------------------------------------------------------
    def _h_kv_put(self, conn, msg):
        added = self.control.kv_put(
            msg.get("ns", ""), msg["key"], msg["value"],
            overwrite=msg.get("overwrite", True),
        )
        return {"added": added}

    def _h_kv_get(self, conn, msg):
        return {"value": self.control.kv_get(msg.get("ns", ""), msg["key"])}

    def _h_kv_keys(self, conn, msg):
        return {
            "keys": self.control.kv_keys(
                msg.get("ns", ""), msg.get("prefix", "")
            )
        }

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def _ensure_entry(self, oid: ObjectID) -> ObjectEntry:
        entry = self.objects.get(oid)
        if entry is None:
            entry = ObjectEntry()
            self.objects[oid] = entry
        return entry

    def _h_put_inline(self, conn, msg):
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.inline = msg["data"]
            entry.size = len(msg["data"])
            entry.state = SEALED
            waiters = entry.waiters
            entry.waiters = []
        self._wake(oid, waiters)
        self._schedule()
        return {}

    def _h_object_sealed(self, conn, msg):
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.size = msg["size"]
            entry.in_shm = True
            entry.state = SEALED
            waiters = entry.waiters
            entry.waiters = []
        self._wake(oid, waiters)
        self._schedule()
        return {}

    def _h_seal_error(self, conn, msg):
        oid = ObjectID(msg["oid"])
        self._seal_error(oid, msg["error"])
        self._schedule()
        return {}

    def _seal_error(self, oid: ObjectID, error: bytes) -> None:
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.error = error
            entry.state = ERRORED
            waiters = entry.waiters
            entry.waiters = []
        self._wake(oid, waiters)

    def _wake(self, oid: ObjectID, waiters: List[tuple]) -> None:
        for conn, mid in waiters:
            conn.reply(mid, self._object_reply(oid))

    def _object_reply(self, oid: ObjectID) -> dict:
        with self._lock:
            entry = self.objects.get(oid)
            if entry is None or entry.state == PENDING:
                return {"pending": True}
            if entry.state == ERRORED:
                return {"error": entry.error}
            if entry.inline is not None:
                return {"inline": entry.inline}
            return {"shm_size": entry.size}

    def _h_get_object(self, conn, msg):
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            if entry.state == PENDING:
                entry.waiters.append((conn, msg["_mid"]))
                return DEFERRED
        return self._object_reply(oid)

    def _h_wait_objects(self, conn, msg):
        oids = [ObjectID(b) for b in msg["oids"]]
        num_returns = msg["num_returns"]
        timeout = msg.get("wait_timeout")
        state = {"done": False}

        def check_and_reply(force: bool = False):
            with self._lock:
                if state["done"]:
                    return
                ready = [
                    o.binary()
                    for o in oids
                    if self.objects.get(o) is not None
                    and self.objects[o].state != PENDING
                ]
                if len(ready) >= num_returns or force:
                    state["done"] = True
                    remaining = [
                        o.binary() for o in oids if o.binary() not in set(ready)
                    ]
                    conn.reply(
                        msg["_mid"], {"ready": ready, "remaining": remaining}
                    )

        with self._lock:
            for o in oids:
                entry = self._ensure_entry(o)
                if entry.state == PENDING:
                    entry.waiters.append(
                        (_CallbackConn(check_and_reply), None)
                    )
        if timeout is not None:
            threading.Timer(timeout, lambda: check_and_reply(force=True)).start()
        check_and_reply()
        return DEFERRED

    def _h_add_ref(self, conn, msg):
        with self._lock:
            for b in msg["oids"]:
                self._ensure_entry(ObjectID(b)).refcount += 1
        return {}

    def _h_del_ref(self, conn, msg):
        to_delete = []
        with self._lock:
            for b in msg["oids"]:
                oid = ObjectID(b)
                entry = self.objects.get(oid)
                if entry is None:
                    continue
                entry.refcount -= 1
                if entry.refcount <= 0 and entry.state != PENDING:
                    to_delete.append((oid, entry.in_shm))
                    del self.objects[oid]
        for oid, in_shm in to_delete:
            # Clients create segments directly; the daemon owns unlink.
            if in_shm:
                self.store.unlink_by_id(oid)
            else:
                self.store.delete(oid)
        return {}

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------
    def _pin_args(self, spec: dict) -> None:
        """Hold a reference on every ObjectRef argument for the task's
        lifetime so caller-side handle drops can't delete an object a
        queued task still needs (reference: ReferenceCounter pins
        submitted-task arguments, reference_count.h)."""
        with self._lock:
            for kind, payload in spec["args"]:
                if kind == "ref":
                    self._ensure_entry(ObjectID(payload)).refcount += 1

    def _unpin_creation_args(self, runtime: "ActorRuntime") -> None:
        """Release an actor's creation-task args exactly once, when the
        actor can no longer restart."""
        with self._lock:
            if runtime.creation_unpinned:
                return
            runtime.creation_unpinned = True
        self._unpin_args(runtime.creation_spec)

    def _unpin_args(self, spec: dict) -> None:
        self._h_del_ref(
            None,
            {
                "oids": [
                    payload
                    for kind, payload in spec["args"]
                    if kind == "ref"
                ]
            },
        )

    def _h_submit_task(self, conn, msg):
        spec = msg["spec"]
        task_id = TaskID(spec["task_id"])
        with self._lock:
            self.tasks[task_id] = TaskEntry(
                spec=spec, retries_left=spec.get("max_retries", 0)
            )
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        self._record_task_event(spec, "PENDING_ARGS_AVAIL")
        self.scheduler.enqueue(
            task_id, ResourceSet(spec.get("resources", {})), spec
        )
        self._schedule()
        return {}

    def _h_create_actor(self, conn, msg):
        spec = msg["spec"]
        actor_id = ActorID(spec["actor_id"])
        info = ActorInfo(
            actor_id=actor_id,
            name=spec.get("name"),
            namespace=spec.get("namespace", "default"),
            state=ACTOR_PENDING_CREATION,
            class_name=spec.get("class_name", ""),
            max_restarts=spec.get("max_restarts", 0),
        )
        self.control.register_actor(info)
        with self._lock:
            self.actors[actor_id] = ActorRuntime(
                creation_spec=spec, info=info
            )
            task_id = TaskID(spec["task_id"])
            self.tasks[task_id] = TaskEntry(spec=spec)
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        self.scheduler.enqueue(
            task_id, ResourceSet(spec.get("resources", {})), spec
        )
        self._schedule()
        return {}

    def _h_submit_actor_task(self, conn, msg):
        spec = msg["spec"]
        actor_id = ActorID(spec["actor_id"])
        task_id = TaskID(spec["task_id"])
        with self._lock:
            runtime = self.actors.get(actor_id)
            self.tasks[task_id] = TaskEntry(
                spec=spec, retries_left=spec.get("max_retries", 0)
            )
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        if runtime is None or runtime.info.state == ACTOR_DEAD:
            self._fail_task_returns(
                spec, "ActorDiedError", "actor is dead"
            )
            return {}
        with self._lock:
            if (
                runtime.info.state == ACTOR_ALIVE
                and runtime.worker_conn_id in self.workers
            ):
                worker = self.workers[runtime.worker_conn_id]
                runtime.inflight[task_id] = spec
                worker.conn.push("execute_task", {"spec": spec})
            else:
                runtime.pending.append(spec)
        return {}

    def _h_task_done(self, conn, msg):
        task_id = TaskID(msg["task_id"])
        error = msg.get("error")  # serialized error payload or None
        system = msg.get("system_error", False)
        with self._lock:
            winfo = self.workers.get(conn.conn_id)
            entry = self.tasks.get(task_id)
        if entry is None:
            return {}
        spec = entry.spec
        if error is not None and system and entry.retries_left > 0:
            # System failures retry with the same task id → same return
            # object ids, the property lineage reconstruction relies on
            # (reference: TaskManager::RetryTaskIfPossible).
            entry.retries_left -= 1
            self._record_task_event(spec, "RETRY")
            self.scheduler.release(task_id)
            self.scheduler.enqueue(
                task_id, ResourceSet(spec.get("resources", {})), spec
            )
        else:
            if error is not None:
                for ret in spec["returns"]:
                    self._seal_error(ObjectID(ret), error)
                self._record_task_event(spec, "FAILED")
            else:
                self._record_task_event(spec, "FINISHED")
            if spec["kind"] == "actor_creation":
                self._on_actor_created(spec, error, conn.conn_id)
                if error is not None:
                    self.scheduler.release(task_id)
                # else: a live actor holds its creation resources until
                # death (_on_actor_worker_death / _mark_actor_dead).
            elif spec["kind"] == "actor_task":
                with self._lock:
                    runtime = self.actors.get(ActorID(spec["actor_id"]))
                    if runtime is not None:
                        runtime.inflight.pop(task_id, None)
            else:
                self.scheduler.release(task_id)
            if spec["kind"] == "actor_creation":
                # Creation args stay pinned while the actor may restart
                # (restarts re-dispatch the same creation spec); a failed
                # creation is permanent death, so release them.
                with self._lock:
                    runtime = self.actors.get(ActorID(spec["actor_id"]))
                if error is not None and runtime is not None:
                    self._unpin_creation_args(runtime)
            else:
                self._unpin_args(spec)
            with self._lock:
                entry.state = "DONE"
        # Return the worker to the pool (actor workers stay pinned).
        with self._lock:
            if winfo is not None and winfo.pinned_actor is None:
                winfo.idle = True
                winfo.current_task = None
        self._schedule()
        return {}

    def _fail_task_returns(self, spec: dict, kind: str, detail: str) -> None:
        from .task_spec import make_error_payload

        payload = make_error_payload(kind, detail)
        for ret in spec["returns"]:
            self._seal_error(ObjectID(ret), payload)
        self._record_task_event(spec, "FAILED")
        if spec["kind"] == "actor_creation":
            with self._lock:
                runtime = self.actors.get(ActorID(spec["actor_id"]))
            if runtime is not None:
                self._unpin_creation_args(runtime)
            else:
                self._unpin_args(spec)
        else:
            self._unpin_args(spec)

    def _h_cancel_task(self, conn, msg):
        task_id = TaskID(msg["task_id"])
        cancelled = self.scheduler.cancel(task_id)
        if cancelled:
            with self._lock:
                entry = self.tasks.get(task_id)
            if entry is not None:
                self._fail_task_returns(
                    entry.spec, "TaskCancelledError", "task was cancelled"
                )
        return {"cancelled": cancelled}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def _on_actor_created(
        self, spec: dict, error, worker_conn_id: int
    ) -> None:
        actor_id = ActorID(spec["actor_id"])
        with self._lock:
            runtime = self.actors.get(actor_id)
            if runtime is None:
                return
            if runtime.info.state == ACTOR_DEAD:
                # Killed while the creation task was queued/running: do
                # not resurrect; release the worker back to the pool.
                worker = self.workers.get(worker_conn_id)
                if worker is not None:
                    worker.pinned_actor = None
                if error is None and worker is not None:
                    # The instance was constructed; recycle the process
                    # so actor state can't leak into later tasks.
                    try:
                        os.kill(worker.pid, 9)
                    except ProcessLookupError:
                        pass
                return
            if error is not None:
                runtime.info.state = ACTOR_DEAD
                self.control.update_actor_state(
                    actor_id, ACTOR_DEAD, death_cause="creation task failed"
                )
                pending = list(runtime.pending)
                runtime.pending.clear()
                # Unpin so _h_task_done returns this worker to the pool.
                worker = self.workers.get(worker_conn_id)
                if worker is not None:
                    worker.pinned_actor = None
            else:
                runtime.info.state = ACTOR_ALIVE
                runtime.worker_conn_id = worker_conn_id
                self.control.update_actor_state(
                    actor_id, ACTOR_ALIVE, node_id=self.node_id
                )
                worker = self.workers.get(worker_conn_id)
                worker.current_task = None
                worker.pinned_actor = actor_id
                pending = []
                while runtime.pending:
                    queued = runtime.pending.popleft()
                    runtime.inflight[TaskID(queued["task_id"])] = queued
                    worker.conn.push("execute_task", {"spec": queued})
        for p in pending:
            self._fail_task_returns(
                p, "ActorDiedError", "actor creation failed"
            )

    def _on_actor_worker_death(self, winfo: WorkerInfo) -> None:
        actor_id = winfo.pinned_actor
        with self._lock:
            runtime = self.actors.get(actor_id)
            if runtime is None:
                return
            can_restart = (
                runtime.info.max_restarts == -1
                or runtime.info.num_restarts < runtime.info.max_restarts
            ) and not self._shutdown
            inflight = list(runtime.inflight.values())
            runtime.inflight.clear()
            creating = (
                self.tasks.get(winfo.current_task)
                if runtime.info.state == ACTOR_PENDING_CREATION
                and winfo.current_task is not None
                else None
            )
        for spec in inflight:
            self._fail_task_returns(
                spec,
                "ActorUnavailableError" if can_restart else "ActorDiedError",
                "actor worker died while executing task",
            )
        if creating is not None and not can_restart:
            self._fail_task_returns(
                creating.spec, "ActorDiedError", "actor died during creation"
            )
        creation_task = TaskID(runtime.creation_spec["task_id"])
        self.scheduler.release(creation_task)
        if can_restart:
            with self._lock:
                runtime.info.num_restarts += 1
                runtime.info.state = ACTOR_RESTARTING
                runtime.worker_conn_id = None
            self.control.update_actor_state(actor_id, ACTOR_RESTARTING)
            self.scheduler.enqueue(
                creation_task,
                ResourceSet(runtime.creation_spec.get("resources", {})),
                runtime.creation_spec,
            )
            self._schedule()
        else:
            self._mark_actor_dead(actor_id, "worker died")

    def _mark_actor_dead(self, actor_id: ActorID, cause: str) -> None:
        with self._lock:
            runtime = self.actors.get(actor_id)
            if runtime is None:
                return
            runtime.info.state = ACTOR_DEAD
            pending = list(runtime.pending)
            runtime.pending.clear()
        self.control.update_actor_state(
            actor_id, ACTOR_DEAD, death_cause=cause
        )
        self._unpin_creation_args(runtime)
        for p in pending:
            self._fail_task_returns(p, "ActorDiedError", cause)

    def _h_kill_actor(self, conn, msg):
        actor_id = ActorID(msg["actor_id"])
        with self._lock:
            runtime = self.actors.get(actor_id)
            if runtime is None:
                return {"ok": False}
            if msg.get("no_restart", True):
                runtime.info.max_restarts = 0  # suppress restart
            winfo = self.workers.get(runtime.worker_conn_id)
            creation_task = TaskID(runtime.creation_spec["task_id"])
        if winfo is not None:
            try:
                os.kill(winfo.pid, 9)
            except ProcessLookupError:
                pass
        else:
            # No live worker: the creation task may still be queued —
            # cancel it so the actor can't resurrect after the kill, and
            # seal its return objects so waiters unblock with an error.
            if self.scheduler.cancel(creation_task):
                self._fail_task_returns(
                    runtime.creation_spec,
                    "ActorDiedError",
                    "actor killed before creation",
                )
            self._mark_actor_dead(actor_id, "killed via kill()")
        return {"ok": True}

    def _h_get_named_actor(self, conn, msg):
        info = self.control.get_named_actor(
            msg.get("namespace", "default"), msg["name"]
        )
        if info is None:
            return {"found": False}
        with self._lock:
            runtime = self.actors.get(info.actor_id)
        return {
            "found": True,
            "actor_id": info.actor_id.binary(),
            "state": info.state,
            "handle_meta": runtime.creation_spec.get("handle_meta")
            if runtime
            else None,
        }

    def _h_get_actor_info(self, conn, msg):
        actor_id = ActorID(msg["actor_id"])
        with self._lock:
            runtime = self.actors.get(actor_id)
        if runtime is None:
            return {"found": False}
        return {
            "found": True,
            "state": runtime.info.state,
            "num_restarts": runtime.info.num_restarts,
        }

    # ------------------------------------------------------------------
    # scheduling + worker pool
    # ------------------------------------------------------------------
    def _schedule(self) -> None:
        if self._shutdown:
            return
        self.scheduler.maybe_dispatch(self._deps_ready, self._try_dispatch)

    def _deps_ready(self, spec: dict) -> bool:
        with self._lock:
            for kind, payload in spec["args"]:
                if kind == "ref":
                    entry = self.objects.get(ObjectID(payload))
                    if entry is None or entry.state == PENDING:
                        return False
        return True

    def _try_dispatch(self, task_id: TaskID, spec: dict) -> bool:
        needs_tpu = spec.get("resources", {}).get("TPU", 0) > 0
        with self._lock:
            worker = next(
                (
                    w
                    for w in self.workers.values()
                    if w.idle and w.is_tpu == needs_tpu
                ),
                None,
            )
            if worker is None:
                if (
                    len(self.workers) + self._spawning < self._max_workers
                ):
                    self._spawn_worker(needs_tpu)
                return False
            worker.idle = False
            worker.current_task = task_id
            if spec["kind"] == "actor_creation":
                worker.pinned_actor = ActorID(spec["actor_id"])
        self._record_task_event(spec, "RUNNING")
        worker.conn.push("execute_task", {"spec": spec})
        return True

    def _spawn_worker(self, needs_tpu: bool = False) -> None:
        self._spawning += 1
        env = dict(os.environ)
        env["RT_SOCKET"] = self.socket_path
        env["RT_WORKER_TPU"] = "1" if needs_tpu else "0"
        if not needs_tpu:
            # CPU workers must not touch (or pay the init cost of) the
            # TPU runtime: hide the chips the way the reference scopes
            # accelerator visibility per worker (reference:
            # _private/accelerators/tpu.py:155 TPU_VISIBLE_CHIPS).
            env["TPU_VISIBLE_CHIPS"] = ""
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # axon site hook gate
        # Workers must import this package regardless of their cwd.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH", "")) if p
        )
        log_path = os.path.join(
            self.session_dir, f"worker-{len(self._worker_procs)}.out"
        )
        with open(log_path, "ab") as log_file:
            # The child holds its own copy of the fd; closing ours
            # immediately avoids leaking one fd per spawn.
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_main"],
                env=env,
                stdout=log_file,
                stderr=subprocess.STDOUT,
            )
        self._worker_procs.append(proc)
        self._watch_worker_start(proc)

    def _watch_worker_start(self, proc: subprocess.Popen) -> None:
        """Detect workers that die before registering (bad env, import
        error) so their spawn slot is reclaimed and the failure is
        surfaced instead of hanging the queue (reference: WorkerPool
        PopWorker failure callbacks, worker_pool.cc:1312)."""

        def watch():
            deadline = time.time() + 30
            while time.time() < deadline:
                if proc.poll() is not None:
                    with self._lock:
                        registered = any(
                            w.pid == proc.pid for w in self.workers.values()
                        )
                        if not registered:
                            self._spawning = max(0, self._spawning - 1)
                            self._spawn_failures += 1
                            failures = self._spawn_failures
                    if not registered and failures >= 3:
                        self._fail_all_queued(
                            "worker processes are crashing at startup; "
                            f"see {self.session_dir}/worker-*.out"
                        )
                    self._schedule()
                    return
                if any(
                    w.pid == proc.pid for w in list(self.workers.values())
                ):
                    return
                time.sleep(0.2)

        threading.Thread(target=watch, daemon=True).start()

    def _fail_all_queued(self, detail: str) -> None:
        with self._lock:
            queued = [
                (tid, spec)
                for tid, (_, spec) in list(self.scheduler._queue.items())
            ]
        for tid, spec in queued:
            if self.scheduler.cancel(tid):
                self._fail_task_returns(spec, "WorkerCrashedError", detail)

    def _on_task_worker_death(self, winfo: WorkerInfo) -> None:
        task_id = winfo.current_task
        with self._lock:
            entry = self.tasks.get(task_id)
        if entry is None:
            return
        self.scheduler.release(task_id)
        if entry.retries_left > 0 and not self._shutdown:
            entry.retries_left -= 1
            self._record_task_event(entry.spec, "RETRY")
            self.scheduler.enqueue(
                task_id,
                ResourceSet(entry.spec.get("resources", {})),
                entry.spec,
            )
            self._schedule()
        else:
            self._fail_task_returns(
                entry.spec, "WorkerCrashedError", "worker process died"
            )

    # ------------------------------------------------------------------
    # introspection / state API
    # ------------------------------------------------------------------
    def _h_cluster_resources(self, conn, msg):
        return {"resources": self.scheduler.total().to_dict()}

    def _h_available_resources(self, conn, msg):
        return {"resources": self.scheduler.available().to_dict()}

    def _h_state_summary(self, conn, msg):
        summary = self.control.summary()
        summary.update(self.store.size_info())
        with self._lock:
            summary["workers"] = len(self.workers)
            summary["queued_tasks"] = self.scheduler.queued_count()
        return {"summary": summary}

    def _h_list_task_events(self, conn, msg):
        return {"events": self.control.list_task_events(msg.get("limit", 1000))}

    def _h_list_nodes(self, conn, msg):
        return {
            "nodes": [
                {
                    "node_id": n.node_id.hex(),
                    "address": n.address,
                    "resources": n.resources,
                    "alive": n.alive,
                    "is_head": n.is_head,
                }
                for n in self.control.nodes.values()
            ]
        }

    def _h_list_actors(self, conn, msg):
        with self._lock:
            return {
                "actors": [
                    {
                        "actor_id": a.info.actor_id.hex(),
                        "name": a.info.name,
                        "state": a.info.state,
                        "class_name": a.info.class_name,
                        "num_restarts": a.info.num_restarts,
                    }
                    for a in self.actors.values()
                ]
            }

    def _record_task_event(self, spec: dict, state: str) -> None:
        if not self.config.task_events_enabled:
            return
        self.control.add_task_event(
            {
                "task_id": spec["task_id"].hex()
                if isinstance(spec["task_id"], bytes)
                else str(spec["task_id"]),
                "name": spec.get("name", ""),
                "kind": spec.get("kind", "normal"),
                "state": state,
                "time": time.time(),
            }
        )

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        for proc in self._worker_procs:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        for proc in self._worker_procs:
            try:
                proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                pass
        self.server.close()
        # Reclaim every live shared-memory object of the session.
        with self._lock:
            shm_oids = [
                oid for oid, e in self.objects.items() if e.in_shm
            ]
        for oid in shm_oids:
            self.store.unlink_by_id(oid)
        self.store.shutdown()


class _CallbackConn:
    """Adapter so wait-waiters can sit in ObjectEntry.waiters."""

    def __init__(self, callback):
        self._callback = callback

    def reply(self, mid, payload):
        self._callback()


def _default_store_bytes() -> int:
    try:
        import psutil  # noqa: PLC0415

        total = psutil.virtual_memory().total
    except Exception:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    return int(total * 0.3)
