"""Per-node daemon: local scheduler + worker pool + object plane,
with the control plane embedded on the head node.

This process plays the role of the reference's raylet (reference:
src/ray/raylet/node_manager.h — worker leasing node_manager.cc:1807,
dependency-gated dispatch local_task_manager.cc:122, worker pool
worker_pool.cc:1312) and, on the head node, also the GCS server
(src/ray/gcs/gcs_server/gcs_server.h). Folding GCS into the head
daemon replaces the reference's separate `gcs_server` binary; the
tables are the same (`gcs.ControlState`).

Topology: every node runs a `NodeDaemon`. The head (`is_head=True`)
owns all control tables, object metadata (locations, refcounts), the
cluster scheduler (policies.py), actor lifecycle decisions, and node
health. Worker nodes (`is_head=False`, `head_address=...`) proxy
control ops to the head, execute tasks forwarded by the head against
their local worker pool, and serve/pull object data node-to-node
(the reference's ObjectManager push/pull plane,
src/ray/object_manager/object_manager.h, chunked per
ray_config_def.h:341). Placement is decided centrally at the head from
heartbeat-refreshed load views — the GCS-scheduling path of the
reference rather than raylet spillback.

Workers and drivers connect over a Unix socket (`rpc.RpcServer`).
Large objects never pass through this process on the node that owns
them: clients write them straight into per-object shared memory and
only the seal notification flows here (the plasma create/seal
protocol, src/ray/object_manager/plasma/store.h).
"""

from __future__ import annotations

import bisect
import math
import os
import queue
import subprocess
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .config import Config
from .gcs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ACTOR_PENDING_CREATION,
    ACTOR_RESTARTING,
    ActorInfo,
    ControlState,
    JobInfo,
    NodeInfo,
)
from .ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from .object_store import ObjectStoreFullError, make_store
from .spilling import FileSpillStorage
from .placement_groups import (
    PGEntry,
    STRATEGIES,
    group_resources,
    place_bundles,
)
from .policies import NodeView, PlacementPolicy
from .rpc import DEFERRED, Connection, RpcClient, RpcError, RpcServer
from .scheduler import LocalScheduler, ResourceSet
from ray_tpu.devtools.lock_witness import make_lock

# Object entry states.
PENDING = "PENDING"
SEALED = "SEALED"
ERRORED = "ERRORED"


def _oob_chunk(chunk: bytes):
    """Wrap an object-transfer chunk so the wire layer ships it as a
    pickle-5 out-of-band buffer: the sender scatter-gathers it straight
    from this memory and the receiver reconstructs a zero-copy view of
    its receive buffer (numpy implements the PickleBuffer protocol;
    raw bytes would be copied back in at load). The puller's
    `buf[off:off+n] = data` assignment accepts the array view as-is."""
    import numpy as np

    return np.frombuffer(chunk, dtype=np.uint8)


@dataclass
class ObjectEntry:
    state: str = PENDING
    size: int = 0
    inline: Optional[bytes] = None  # small objects live here (head)
    error: Optional[bytes] = None  # serialized TaskError payload
    in_shm: bool = False  # data present in THIS node's store
    refcount: int = 1  # head-only: owner refcount
    waiters: List[tuple] = field(default_factory=list)  # (conn, mid)
    # head-only: which nodes hold a shm copy + meta subscribers.
    locations: Set[bytes] = field(default_factory=set)
    meta_waiters: List[tuple] = field(default_factory=list)
    pulling: bool = False
    reconstructing: bool = False
    #: Data written to this node's spill storage; the shm copy may be
    #: gone but the object is still servable locally (reference:
    #: ObjectTableData spilled_url, gcs.proto).
    spilled: bool = False
    # Owner attribution (reference: ObjectTableData owner/spilled
    # fields; the memory ledger's per-job accounting rides these).
    #: Hex job id of the creating client; "" = unattributed.
    owner_job: str = ""
    #: Creating context: "driver", "task:<hex>" or "actor:<hex>".
    owner: str = ""
    #: Pid of the creating client ON THE SEALING NODE (0 elsewhere);
    #: probed for liveness by that node's memory report only.
    owner_pid: int = 0
    #: Wall time of the first seal (leak-age anchor).
    created_ts: float = 0.0
    #: How THIS node's shm copy materialised: "" (sealed locally),
    #: "pull" (remote arena), "pull_spill" (remote spill file) or
    #: "restore" (this node's own spill file). Drives get-path
    #: provenance classification in worker replies.
    source: str = ""
    #: Hex node id the copy was pulled from ("" unless source is a
    #: pull kind).
    src_node: str = ""
    #: True only between the materialising event and its waiter wake:
    #: gets that actually waited on the pull/restore bill it; later
    #: gets of the (now warm) copy classify as local arena hits.
    source_fresh: bool = False


@dataclass
class WorkerInfo:
    conn: Connection
    worker_id: WorkerID
    pid: int
    idle: bool = True
    #: Wall time of the last busy->idle transition (drives idle reap).
    idle_since: float = field(default_factory=time.time)
    is_tpu: bool = False
    pinned_actor: Optional[ActorID] = None
    current_task: Optional[TaskID] = None
    #: Direct-transport endpoint served by the worker process
    #: (reference: the worker's gRPC server in core_worker.h).
    direct_address: Optional[str] = None
    #: conn_id of the driver holding this worker via request_lease.
    leased_by: Optional[int] = None


@dataclass
class TaskEntry:
    spec: dict
    state: str = "PENDING"
    retries_left: int = 0
    node: Optional[bytes] = None  # head-only: forwarded-to node


@dataclass
class ActorHost:
    """Per-node hosting record: binds an actor to a local worker
    (reference: the executing side of ActorTaskSubmitter — the worker
    the creation task leased, transport/actor_task_submitter.h)."""

    creation_spec: dict
    worker_conn_id: Optional[int] = None
    pending: deque = field(default_factory=deque)
    inflight: Dict[TaskID, dict] = field(default_factory=dict)


@dataclass
class ActorRuntime:
    """Head-side authoritative actor record (reference:
    GcsActorManager state machine, design_docs/actor_states.rst)."""

    creation_spec: dict
    info: ActorInfo
    node: Optional[bytes] = None  # hosting node id
    pending: deque = field(default_factory=deque)  # queued while !ALIVE
    inflight: Dict[TaskID, dict] = field(default_factory=dict)
    creation_unpinned: bool = False


def _summarize_steps(records: List[dict]) -> dict:
    """Digest per-step/per-rank phase records into the two views the
    doctor needs: per-worker step-time stats (straggler detection) and
    per-step gang skew (max - min step_ms across the ranks that
    reported that step index).

    Stats are computed over the MOST RECENT job only: mixing two
    jobs' same-rank records (concurrent tenants, or back-to-back runs
    within the ring) would yield phantom stragglers and meaningless
    skew. Older jobs stay in the raw ring (`step_records`); the
    summary reports how many distinct jobs it saw."""
    jobs: Dict[str, float] = {}
    for rec in records:
        job = str(rec.get("job", ""))
        t = float(rec.get("time", 0.0))
        if t >= jobs.get(job, -1.0):
            jobs[job] = t
    if len(jobs) > 1:
        current = max(jobs, key=lambda j: jobs[j])
        records = [
            r for r in records if str(r.get("job", "")) == current
        ]
    by_step: Dict[int, Dict[int, dict]] = {}
    by_rank: Dict[int, List[dict]] = {}
    for rec in records:
        rank = int(rec.get("rank", 0))
        by_step.setdefault(int(rec.get("step", 0)), {})[rank] = rec
        by_rank.setdefault(rank, []).append(rec)
    skew: Dict[int, float] = {}
    for step, ranks in by_step.items():
        # Warmup (first-report) records derive step_ms from a wall
        # anchored at session construction — setup time, not a step;
        # ranks differ in setup time, so including them fakes skew.
        values = [
            float(r.get("step_ms", 0.0))
            for r in ranks.values()
            if not r.get("warmup")
        ]
        if len(values) >= 2:
            skew[step] = round(max(values) - min(values), 3)
    workers: Dict[int, dict] = {}
    for rank, recs in by_rank.items():
        timed = [r for r in recs if not r.get("warmup")] or recs
        step_ms = sorted(float(r.get("step_ms", 0.0)) for r in timed)
        row = {
            # The sample count BEHIND the stats: warmup records are
            # excluded, so the doctor's `steps >= 3` straggler gate
            # never convicts on fewer measured steps than it claims.
            "steps": len(timed),
            "p50_step_ms": round(step_ms[len(step_ms) // 2], 3),
            "max_step_ms": round(step_ms[-1], 3),
            "mean_step_ms": round(sum(step_ms) / len(step_ms), 3),
        }
        for phase in ("data_wait_ms", "h2d_ms", "wall_ms"):
            values = [
                float(r[phase]) for r in timed if phase in r
            ]
            if values:
                row["mean_" + phase] = round(
                    sum(values) / len(values), 3
                )
        inflight = [
            int(r["ckpt_inflight"])
            for r in recs
            if "ckpt_inflight" in r
        ]
        if inflight:
            row["max_ckpt_inflight"] = max(inflight)
        workers[rank] = row
    return {
        "workers": workers,
        "skew_ms": skew,
        "max_skew_ms": max(skew.values(), default=0.0),
        "steps_observed": len(by_step),
        "jobs_observed": len(jobs),
    }


class NodeDaemon:
    def __init__(
        self,
        session_dir: str,
        resources: Dict[str, float],
        config: Config,
        is_head: bool = True,
        head_address: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        listen_host: Optional[str] = None,
        listen_port: int = 0,
    ):
        """A per-host daemon (raylet analog). Local workers always ride
        the session Unix socket; passing `listen_host` additionally
        binds a TCP listener (DCN transport) whose address is what the
        node advertises cluster-wide — the configuration for real
        multi-host deployments (reference: raylet's gRPC
        NodeManagerService port, node_manager.proto:406)."""
        self.session_dir = session_dir
        self.config = config
        # Before any make_lock() below: the witness only instruments
        # locks created after it is installed.
        from ray_tpu.devtools.lock_witness import configure as _witness_configure

        _witness_configure(config)
        self.is_head = is_head
        self.node_id = NodeID.from_random()
        self.socket_path = os.path.join(session_dir, "hostd.sock")
        os.makedirs(session_dir, mode=0o700, exist_ok=True)
        try:
            # exist_ok skips mode application on pre-existing dirs;
            # the session dir's permissions gate unix-socket access
            # (rpc.py _frame_mac), so enforce them regardless.
            os.chmod(session_dir, 0o700)
        except OSError:
            pass

        capacity = config.object_store_memory or _default_store_bytes()
        self.store = make_store(
            self.node_id.hex(),
            capacity,
            on_evict=self._on_store_evict,
            use_native=config.use_native_object_store,
        )
        self.spill: Optional[FileSpillStorage] = None
        if config.object_spilling_enabled:
            self.spill = FileSpillStorage(
                os.path.join(
                    session_dir, "spilled_objects", self.node_id.hex()[:8]
                )
            )
        self._spill_lock = make_lock("daemon.spill")
        # Primary-copy pins: the daemon holds a read pin on every object
        # sealed by a local client so LRU eviction can never destroy the
        # only copy — store-full becomes a spill trigger instead
        # (reference: raylet pins primary copies via PinObjectIDs,
        # local_object_manager.h:41; spilling releases the pin).
        self._primary_pins: Dict[ObjectID, object] = {}
        self.scheduler = LocalScheduler(ResourceSet(resources))
        self.resources = dict(resources)
        self.labels = dict(labels or {})

        self._lock = make_lock("daemon.state", "rlock")
        # Core metrics (reference: stats/metric_defs.cc central
        # registry): monotonic event counters bumped at the few sites
        # where things happen; gauges computed at scrape
        # (metric_defs.collect).
        from .metric_defs import CoreCounters

        self.core_counters = CoreCounters()
        self.started_at = time.time()
        self.objects: Dict[ObjectID, ObjectEntry] = {}
        self.tasks: Dict[TaskID, TaskEntry] = {}
        self.actor_hosts: Dict[ActorID, ActorHost] = {}
        self.workers: Dict[int, WorkerInfo] = {}  # conn_id -> info
        self.drivers: Dict[int, JobID] = {}  # conn_id -> job
        self._spawning = 0
        self._spawn_watchlist: list = []
        self._spawn_watch_lock = threading.Lock()
        self._spawn_watcher: Optional[threading.Thread] = None
        #: Same-host peers' arenas attached for shm-copy pulls,
        #: keyed by arena path (see _pull_same_host).
        self._peer_arenas: Dict[str, object] = {}
        self._fork_server = None  # warm worker template (lazy)
        self._fork_server_lock = threading.Lock()
        # Worker spawns run on a dedicated thread: the fork-server
        # handshake (and a cold Popen on a loaded box) does blocking
        # I/O that must never run under self._lock — every dispatch,
        # registration and heartbeat handler needs that lock.
        self._spawn_queue: "queue.Queue" = queue.Queue()
        self._spawn_thread: Optional[threading.Thread] = None
        self._spawn_failures = 0
        #: Cumulative (never reset): workers that died before
        #: registering. Test fixtures assert this stays 0 — a startup
        #: crash is a bug even when a later spawn succeeded
        #: (the consecutive counter above resets on success).
        self._spawn_crash_total = 0
        #: Every pid that has EVER registered as a worker. The spawn
        #: watcher must consult history, not the live `workers` dict: a
        #: short-lived worker (one fast trial, then exit) can register
        #: AND exit between two watcher ticks — judging only by "is it
        #: registered right now" counts that healthy lifecycle as a
        #: startup crash (observed: TPE trials under heavy box load).
        self._registered_pids_ever: set = set()
        self._shutdown = False
        self._worker_procs: List[subprocess.Popen] = []
        # Direct-transport leases: lease_id -> (worker_conn_id,
        # driver_conn_id). The worker is out of the shared pool while
        # leased; its resources stay reserved in the scheduler under
        # the lease id (reference: raylet worker leases,
        # node_manager.cc:1807 HandleRequestWorkerLease).
        self.leases: Dict[str, tuple] = {}
        self._lease_counter = 0
        # actor_id -> [(conn, mid)] waiting for the actor's direct
        # address (replied when the actor becomes ALIVE or DEAD).
        self._actor_addr_waiters: Dict[ActorID, list] = {}
        # Driver connections subscribed to worker log streaming
        # (reference: log_monitor.py publishes tailed lines; drivers
        # print them). conn_id -> Connection. On the head this also
        # holds worker-node relay connections.
        self._log_subscribers: Dict[int, Connection] = {}
        # Worker-node cache of "does the head have log subscribers",
        # piggybacked on heartbeat replies.
        self._head_logs_wanted = False
        # Head-side resource-sync versions: node_id -> last version
        # whose load snapshot was applied (versioned delta heartbeats).
        self._node_sync_versions: Dict[bytes, int] = {}
        # Finished tracing spans (head only; own ring so span-heavy
        # apps and task-event-heavy apps can't evict each other).
        self._spans: deque = deque(maxlen=config.task_events_max_buffer)
        # Per-step, per-worker phase records from train telemetry
        # (head only; ride the metrics pipe as kind="step" records).
        # Bounded ring: old steps age out, the skew computation only
        # ever wants the recent window anyway.
        self._step_records: deque = deque(
            maxlen=config.task_events_max_buffer
        )
        # XLA compile watch (head): per-program digest rings folded
        # from kind="compile" metrics-pipe records
        # (_private/compile_watch.py fold_record — the same structure
        # the per-process registry keeps, so detect_storms serves
        # both). Bounded by construction: program names are
        # registered families, digests ring-capped per program.
        self._compile_programs: Dict[str, dict] = {}
        # Head time-series ring: periodic compacted snapshots of the
        # metric table so p50/p99 TRENDS survive past the live
        # reservoir (`/api/timeseries`, `ray_tpu metrics snapshot`).
        from .timeseries import TimeSeriesStore

        self._timeseries = TimeSeriesStore(
            config.metrics_timeseries_max_snapshots
        )
        # Cluster memory & per-job usage ledger (head: aggregates the
        # per-node reports; every node builds its own report on the
        # memory-report tick).
        from .memory_ledger import MemoryLedger

        self._memory_ledger = MemoryLedger(
            max_owner_series=config.memory_report_topk
        )
        self._memory_folded_at = 0.0
        # Per-job spill/restore OP counts on THIS node (cumulative;
        # ride the node memory report so the head's ledger attributes
        # rt_object_spills/restores_total to the job that forced them).
        self._job_spill_ops: Dict[str, int] = {}
        self._job_restore_ops: Dict[str, int] = {}
        # This process's flight recorder obeys the cluster config
        # (env RT_flight_recorder_enabled already applied at import).
        from .compile_watch import configure as _compile_configure
        from .flight_recorder import configure as _flight_configure

        _flight_configure(config)
        _compile_configure(config)

        max_workers = config.max_workers_per_node or max(
            4, int(4 * resources.get("CPU", 1))
        )
        self._max_workers = max_workers
        # In-flight worker-process startups allowed at once (reference:
        # worker_pool.cc maximum_startup_concurrency = num_cpus). Actor
        # creations spawn past _max_workers but never past this gate.
        self._startup_concurrency = max(
            2, int(resources.get("CPU", 1))
        )

        # Head-only state.
        self.control: Optional[ControlState] = None
        self.actor_runtimes: Dict[ActorID, ActorRuntime] = {}
        self._policy = PlacementPolicy(
            config.scheduler_spread_threshold,
            config.scheduler_top_k_fraction,
        )
        self._infeasible: Dict[TaskID, dict] = {}  # spec by task id
        self._node_clients: Dict[bytes, RpcClient] = {}
        self._node_conns: Dict[int, bytes] = {}  # conn_id -> node_id
        self._memory_monitor = None
        # Application metrics (head): name -> aggregate state
        # (reference: metrics agent aggregation, _private/metrics_agent
        # .py; serving role of the OpenCensus registry).
        self._metrics_table: Dict[str, dict] = {}
        # (sender, seq) pairs already folded into the table: senders
        # retry sealed batches until acknowledged, so a batch whose
        # reply was lost arrives again — applying it twice would
        # silently inflate every counter it carries. Per sender:
        # [high-water mark, out-of-order seqs above it] — in-order
        # delivery keeps the set empty (O(1) resident per sender for
        # the head's lifetime); only a trim-induced seq gap parks
        # seqs in the set until the gap is passed.
        self._metrics_seen: Dict[str, list] = {}
        #: Standing autoscaler capacity target (head only; sdk
        #: request_resources — REPLACE semantics, cleared by []).
        self._resource_requests: List[dict] = []
        # Placement groups: head-side registry + node-side reserved
        # bundles ((pg_id, index) -> {"resources", "committed"}).
        self.pgs: Dict[bytes, PGEntry] = {}
        self._bundles: Dict[tuple, dict] = {}
        # Serializes the 2PC against concurrent retries/removals
        # (reentrant: a local commit inside the 2PC may re-enter
        # scheduling); the non-blocking gate stops _schedule()-driven
        # retries from recursing (place -> commit -> _schedule -> place).
        self._pg_mutex = make_lock("daemon.pg", "rlock")
        self._pg_retry_gate = threading.Lock()
        # Node-only state.
        self.head: Optional[RpcClient] = None
        self._peer_clients: Dict[str, RpcClient] = {}  # address -> client
        self._hb_thread: Optional[threading.Thread] = None

        self.server = RpcServer(self.socket_path)
        listen_host = listen_host or config.node_listen_host or None
        if listen_host:
            self.address = self.server.add_listener(
                f"tcp://{listen_host}:"
                f"{listen_port or config.node_listen_port}"
            )
        else:
            self.address = self.socket_path
        for name in [
            "register_client",
            "kv_put",
            "kv_get",
            "kv_del",
            "kv_keys",
            "submit_task",
            "submit_tasks",
            "submit_actor_task",
            "create_actor",
            "get_object",
            "get_objects",
            "wait_objects",
            "put_inline",
            "object_sealed",
            "seal_error",
            "task_done",
            "del_ref",
            "add_ref",
            "get_named_actor",
            "get_actor_info",
            "kill_actor",
            "cancel_task",
            "cluster_resources",
            "available_resources",
            "state_summary",
            "list_task_events",
            "list_nodes",
            "list_actors",
            "list_objects",
            "cluster_load",
            "request_resources",
            "metrics_record",
            "metrics_summary",
            "metrics_timeseries",
            # memory ledger (reports flow node -> head; the summary
            # serves `ray_tpu memory` and /api/memory)
            "memory_report",
            "memory_summary",
            # data plane (ISSUE 20): the transfer matrix and the
            # object location/size index
            "transfer_summary",
            "object_locations",
            "event_stats",
            "profile_worker",
            # XLA observability: coordinated gang profiling + the
            # head's folded compile table (verdict.compile's data)
            "profile_gang",
            "compile_summary",
            # flight recorder / stall doctor (all nodes; diagnose and
            # step_summary forward to the head)
            "flight_recorder",
            "lock_witness",
            "worker_inspect",
            "step_summary",
            "diagnose",
            "ping",
            # object data plane (all nodes)
            "pull_object",
            "delete_object",
            # placement groups (API on head; bundle 2PC on all nodes)
            "create_placement_group",
            "remove_placement_group",
            "placement_group_state",
            "placement_group_table",
            "prepare_bundle",
            "commit_bundle",
            "release_bundle",
            # head control plane (worker nodes call these on the head)
            "register_node",
            "node_heartbeat",
            "get_object_meta",
            "task_finished",
            "actor_created",
            "actor_worker_died",
            "object_evicted",
            # head -> node forwards
            "schedule_task",
            "actor_task",
            "kill_actor_local",
            "cancel_local",
            # direct task transport (placement-only daemon role)
            "request_lease",
            "release_lease",
            "actor_address",
            "task_event",
            "task_counts",
            # tracing spans (all nodes forward to the head's ring)
            "span_event",
            "list_spans",
            # object spilling (all nodes)
            "spill_request",
            # pubsub (subscribe on any node; events forward to head)
            "subscribe_logs",
            "unsubscribe_logs",
            "log_batch",
            "publish_event",
            # head fault tolerance
            "node_resync",
        ]:
            self.server.register(name, getattr(self, "_h_" + name))
        self.server.register("_disconnect", self._h_disconnect)

        if is_head:
            self.control = ControlState(config.task_events_max_buffer)
            if config.gcs_fault_tolerance:
                self._restore_control_state()
            self.control.register_node(
                NodeInfo(
                    node_id=self.node_id,
                    address=self.address,
                    resources=dict(resources),
                    labels=self.labels,
                    is_head=True,
                    available=dict(resources),
                )
            )
        else:
            assert head_address, "worker node needs head_address"
            self.head_address = head_address

    def _restore_control_state(self) -> None:
        """Head fault tolerance (reference: GCS restart over its Redis
        store, node_manager.cc:1189 HandleNotifyGCSRestart): replay the
        session's op log into the control tables and resurrect actor
        runtime records; worker nodes re-register and resync via their
        heartbeat loop when they notice the new head."""
        from .gcs import StateLog

        log_path = os.path.join(self.session_dir, "gcs_oplog.bin")
        ops = StateLog.replay(log_path)
        extra = self.control.restore(ops) if ops else []
        self._restored_pending_creations = []
        for op in extra:
            if op[0] != "actor_spec":
                continue
            spec = op[1]
            actor_id = ActorID(spec["actor_id"])
            info = self.control.actors.get(actor_id)
            if info is None or info.state == ACTOR_DEAD:
                continue
            runtime = ActorRuntime(creation_spec=spec, info=info)
            if info.node_id is not None:
                runtime.node = info.node_id.binary()
            self.actor_runtimes[actor_id] = runtime
            if info.state in (
                ACTOR_PENDING_CREATION, ACTOR_RESTARTING,
            ):
                # Creation was in flight when the head died; the
                # scheduler queue was memory-only, so it must be
                # re-dispatched (after start(), when listeners are up).
                # _h_schedule_task's already-hosting guard keeps a
                # surviving node that finished the creation from
                # getting a duplicate instance.
                self._restored_pending_creations.append(spec)
        self.control.log = StateLog(log_path)

    def _redispatch_restored_creations(self) -> None:
        for spec in getattr(self, "_restored_pending_creations", ()):
            task_id = TaskID(spec["task_id"])
            with self._lock:
                self.tasks[task_id] = TaskEntry(spec=spec)
            try:
                self._submit_cluster(spec)
            except Exception:
                pass
        self._restored_pending_creations = []

    def start(self) -> None:
        self.server.start()
        # Launch the fork-server template early (non-blocking) so its
        # one-time import phase overlaps daemon startup instead of
        # stalling the first worker spawn.
        self._ensure_fork_server()
        if self.is_head:
            self._redispatch_restored_creations()
        threading.Thread(
            target=self._maintenance_loop, daemon=True,
            name=f"maint:{self.node_id.hex()[:8]}",
        ).start()
        if (
            self.is_head
            and self.config.metrics_timeseries_interval_s > 0
        ):
            threading.Thread(
                target=self._timeseries_loop, daemon=True,
                name=f"tsdb:{self.node_id.hex()[:8]}",
            ).start()
        if self.config.log_to_driver:
            threading.Thread(
                target=self._log_monitor_loop, daemon=True,
                name=f"logs:{self.node_id.hex()[:8]}",
            ).start()
        # Prestart under the lock (matching every other _spawn_worker
        # call site — _spawning is a plain counter), clamped so at
        # least one pool slot stays free for a differently-typed (TPU)
        # worker: prestarted workers are CPU-type and nothing reaps
        # idle workers, so filling the pool would starve TPU tasks.
        with self._lock:
            headroom = max(0, self._max_workers - 1) - len(
                self.workers
            ) - self._spawning
            for _ in range(
                min(self.config.worker_prestart_count, max(0, headroom))
            ):
                self._spawn_worker()
        if self.config.memory_monitor_refresh_ms > 0:
            from .memory_monitor import MemoryMonitor

            self._memory_monitor = MemoryMonitor(
                self.config.memory_usage_threshold,
                self.config.memory_monitor_refresh_ms / 1000.0,
                self._oom_candidates,
                self._oom_kill,
            )
            self._memory_monitor.start()
        if not self.is_head:
            self.head = RpcClient(
                self.head_address, push_handler=self._on_head_push
            )
            self.head.set_on_reconnect(self._on_head_reconnect)
            self.head.call(
                "register_node",
                node_id=self.node_id.binary(),
                address=self.address,
                resources=self.resources,
                labels=self.labels,
            )
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"hb:{self.node_id.hex()[:8]}",
            )
            self._hb_thread.start()
        if self.config.memory_report_interval_s > 0:
            # After the head client exists (worker nodes push their
            # reports over it); the head folds its own report locally.
            threading.Thread(
                target=self._memory_report_loop, daemon=True,
                name=f"mem:{self.node_id.hex()[:8]}",
            ).start()

    # ------------------------------------------------------------------
    # registration / lifecycle
    # ------------------------------------------------------------------
    def _h_register_client(self, conn: Connection, msg: dict):
        role = msg["role"]
        if role == "worker":
            info = WorkerInfo(
                conn=conn,
                worker_id=WorkerID.from_random(),
                pid=msg["pid"],
                is_tpu=bool(msg.get("is_tpu", False)),
                direct_address=msg.get("direct_address"),
            )
            with self._lock:
                self.workers[conn.conn_id] = info
                self._registered_pids_ever.add(msg["pid"])
                self._spawning = max(0, self._spawning - 1)
                self._spawn_failures = 0
            conn.metadata["role"] = "worker"
            self._schedule()
            return {
                "node_id": self.node_id.binary(),
                "worker_id": info.worker_id.binary(),
                "store_capacity": self.store.size_info()["capacity"],
                "config": self.config.to_dict(),
            }
        # driver
        if not self.is_head:
            # Drivers attach to the head, which owns job state. (The
            # reference lets drivers attach to any raylet; divergence
            # documented in SURVEY §7 — centralized control plane.)
            raise RuntimeError(
                "drivers must connect to the head node address"
            )
        job_id = self.control.next_job_id()
        self.control.add_job(
            JobInfo(
                job_id=job_id,
                driver_pid=msg["pid"],
                start_time=time.time(),
                entrypoint=msg.get("entrypoint", ""),
            )
        )
        with self._lock:
            self.drivers[conn.conn_id] = job_id
        conn.metadata["role"] = "driver"
        return {
            "node_id": self.node_id.binary(),
            "job_id": job_id.binary(),
            "store_capacity": self.store.size_info()["capacity"],
            "config": self.config.to_dict(),
        }

    def _h_register_node(self, conn, msg):
        """A worker-node daemon joins the cluster (head only)."""
        node_id = NodeID(msg["node_id"])
        with self._lock:
            # (Re-)registration resets NodeInfo.available to totals —
            # any previously acked load snapshot no longer describes
            # what this table holds, so force the node to resend.
            self._node_sync_versions.pop(node_id.binary(), None)
        self.control.register_node(
            NodeInfo(
                node_id=node_id,
                address=msg["address"],
                resources=dict(msg["resources"]),
                labels=dict(msg.get("labels") or {}),
                available=dict(msg["resources"]),
            )
        )
        conn.metadata["role"] = "node"
        with self._lock:
            self._node_conns[conn.conn_id] = node_id.binary()
        self._retry_pending_pgs()
        self._retry_infeasible()
        return {"ok": True}

    def _h_node_heartbeat(self, conn, msg):
        node_id = NodeID(msg["node_id"])
        info = self.control.nodes.get(node_id)
        if info is None:
            # Head restarted without state for this node (or the node
            # outlived a mark-dead): ask it to re-register + resync.
            return {"ok": False, "unknown_node": True}
        info.last_heartbeat = time.time()
        info.alive = True  # a heartbeating node is alive
        self.core_counters.bump("heartbeats")
        if "core_metrics" in msg:
            info.core_metrics = dict(msg["core_metrics"])
        version = int(msg.get("version", 0))
        if "available" in msg:
            # Payload present: apply + ack this version. Liveness-only
            # beats (unchanged state) leave the last snapshot in place.
            info.available = dict(msg.get("available") or {})
            info.queued = int(msg.get("queued", 0))
            # Totals change when placement-group bundles commit/release
            # (group resources are added to the node pool).
            total = msg.get("total")
            if total is not None:
                info.resources = dict(total)
            with self._lock:
                self._node_sync_versions[msg["node_id"]] = version
            acked = version
        else:
            with self._lock:
                acked = self._node_sync_versions.get(msg["node_id"], -1)
        # Parked tasks (forward raced a node death, or no feasible node
        # yet) and pending placement groups get another placement
        # attempt on the heartbeat tick.
        with self._hot_lock("heartbeat"):
            any_parked = bool(self._infeasible)
            any_pending_pg = any(
                e.state in ("PENDING", "RESCHEDULING")
                for e in self.pgs.values()
            )
        if any_pending_pg:
            self._retry_pending_pgs()
        if any_parked:
            self._retry_infeasible()
        with self._lock:
            logs_wanted = any(
                "log_lines" in chans
                for _, chans in self._log_subscribers.values()
            )
        return {
            "ok": True,
            "logs_wanted": logs_wanted,
            "acked_version": acked,
        }

    def _heartbeat_loop(self) -> None:
        # Versioned resource sync (reference: ray_syncer's versioned
        # resource messages, common/ray_syncer): the load snapshot only
        # rides the heartbeat when it CHANGED since the head's last
        # ack — an idle 1000-node cluster heartbeats liveness-only.
        version = 0
        last_acked = -1
        last_state = None
        beats = 0
        while not self._shutdown:
            try:
                state = (
                    self.scheduler.available().to_dict(),
                    self.scheduler.total().to_dict(),
                    self.scheduler.queued_count(),
                )
                if state != last_state:
                    version += 1
                    last_state = state
                kwargs = {
                    "node_id": self.node_id.binary(),
                    "version": version,
                    "timeout": 10.0,
                }
                if version != last_acked:
                    kwargs.update(
                        available=state[0], total=state[1],
                        queued=state[2],
                    )
                # Core metrics ride changed-state beats plus a slow
                # refresh tick, so idle nodes still stay liveness-only
                # on the wire most of the time (metric_defs docstring).
                if version != last_acked or beats % 20 == 0:
                    from .metric_defs import collect

                    kwargs["core_metrics"] = collect(self)
                beats += 1
                reply = self.head.call("node_heartbeat", **kwargs)
                if reply.get("acked_version") == version:
                    last_acked = version
                self._head_logs_wanted = bool(reply.get("logs_wanted"))
                if reply.get("unknown_node"):
                    last_acked = -1  # full snapshot after re-register
                    self._resync_with_head()
            except Exception:
                if self._shutdown:
                    return
                # Head connection lost — likely a head restart
                # (reference: raylet resync on HandleNotifyGCSRestart,
                # node_manager.cc:1189). Re-register and re-report our
                # live actors + sealed objects once it is back. The
                # (possibly new) head's view of our load is unknown,
                # so the next beat must carry the full snapshot.
                last_acked = -1
                try:
                    self._resync_with_head()
                except Exception:
                    pass
            # Reclaim arena reader pins of crashed/OOM-killed workers so
            # their slots become evictable again (plasma reclaims on
            # client disconnect; the serverless arena uses pid liveness).
            reap = getattr(self.store, "reap_dead_pins", None)
            if reap is not None:
                try:
                    reap()
                except Exception:
                    pass
            time.sleep(self.config.heartbeat_interval_s)

    def _resync_with_head(self) -> None:
        """Re-attach to a (possibly restarted) head: re-register this
        node and re-report locally-hosted actors and sealed objects so
        the head's directory is rebuilt (reference: raylet-side state
        report after HandleNotifyGCSRestart)."""
        self.head.call(
            "register_node",
            node_id=self.node_id.binary(),
            address=self.address,
            resources=self.resources,
            labels=self.labels,
            retries=5,
            timeout=10.0,
        )
        with self._lock:
            actors = [aid.binary() for aid in self.actor_hosts]
            objects = [
                (oid.binary(), entry.size)
                for oid, entry in self.objects.items()
                if entry.in_shm and entry.state == SEALED
            ]
        self.head.call(
            "node_resync",
            node_id=self.node_id.binary(),
            actors=actors,
            objects=objects,
            timeout=10.0,
        )
        with self._lock:
            has_subs = bool(self._log_subscribers)
        if has_subs:
            # A restarted head lost our relay subscription.
            self._ensure_log_relay()

    def _h_node_resync(self, conn, msg):
        """A worker node re-reports its live state after a head
        restart (head only)."""
        node_id = msg["node_id"]
        for actor_binary in msg.get("actors", ()):
            actor_id = ActorID(actor_binary)
            with self._lock:
                runtime = self.actor_runtimes.get(actor_id)
            if runtime is None or runtime.info.state == ACTOR_DEAD:
                continue
            with self._lock:
                runtime.node = node_id
                runtime.info.state = ACTOR_ALIVE
            self.control.update_actor_state(
                actor_id, ACTOR_ALIVE, node_id=NodeID(node_id)
            )
            self._wake_actor_addr_waiters(actor_id)
        with self._lock:
            for oid_binary, size in msg.get("objects", ()):
                entry = self._ensure_entry(ObjectID(oid_binary))
                entry.state = SEALED
                entry.size = size
                entry.locations.add(node_id)
        return {"ok": True}

    def _h_disconnect(self, conn: Connection, msg: dict):
        if self._shutdown:
            # Dying daemons must not report their own worker kills as
            # task failures — the head's node-death path owns recovery.
            return {}
        with self._lock:
            winfo = self.workers.pop(conn.conn_id, None)
            self.drivers.pop(conn.conn_id, None)
            dead_node = self._node_conns.pop(conn.conn_id, None)
            if winfo is not None:
                # Keep the registration-history set bounded: the spawn
                # watcher usually consumes the pid within a tick, but a
                # watch entry that expired before a slow registration
                # would otherwise pin the pid forever.
                self._registered_pids_ever.discard(winfo.pid)
        if winfo is not None:
            # A disconnecting worker provably registered — resolve any
            # still-pending spawn watch for its pid HERE, not via the
            # history set (which the line above just pruned): a
            # starved watcher that only woke after this disconnect
            # would otherwise see "exited, never registered" and count
            # a healthy short-lived worker as a startup crash.
            with self._spawn_watch_lock:
                self._spawn_watchlist[:] = [
                    e for e in self._spawn_watchlist
                    if e[0].pid != winfo.pid
                ]
        self._drop_log_subscriber(conn.conn_id)
        if dead_node is not None:
            self._on_node_death(dead_node)
            return {}
        if winfo is None:
            self._release_driver_leases(conn.conn_id)
            return {}
        # Worker died (reference: raylet detects worker death via the
        # socket, node_manager.cc:1089 publishes WorkerDeltaData).
        if winfo.leased_by is not None:
            # Leased worker died: free the lease's reservation; the
            # driver sees its direct connection break and handles
            # retry/failure submitter-side.
            with self._lock:
                lease_ids = [
                    lid
                    for lid, (wc, _) in self.leases.items()
                    if wc == conn.conn_id
                ]
            for lid in lease_ids:
                with self._lock:
                    self.leases.pop(lid, None)
                self.scheduler.release(lid)
            self._schedule()
        elif winfo.pinned_actor is not None:
            self._on_actor_worker_death(winfo)
        elif winfo.current_task is not None:
            self._on_task_worker_death(winfo)
        return {}

    def _h_ping(self, conn, msg):
        return {"ok": True, "node_id": self.node_id.binary()}

    # ------------------------------------------------------------------
    # direct task transport: worker leases + actor addresses
    # (reference: node_manager.cc:1807 HandleRequestWorkerLease;
    # the submitter-side protocol lives in _private/direct.py)
    # ------------------------------------------------------------------
    def _h_request_lease(self, conn, msg):
        """Lease an idle local worker to a driver. Queued through the
        LocalScheduler as a pseudo-task so resource accounting and
        FIFO fairness are shared with daemon-scheduled work."""
        if not self.is_head:
            # Drivers attach to the head (enforced at register); a
            # lease request reaching a worker node is out of contract.
            return {"unavailable": True}
        self.core_counters.bump("lease_requests")
        resources = dict(msg.get("resources") or {})
        request = ResourceSet(resources)
        if not request.fits_in(self.scheduler.total()):
            # Locally infeasible (possibly transiently, under PG
            # reservations): the daemon path owns placement then.
            return {"unavailable": True}
        with self._lock:
            self._lease_counter += 1
            lease_id = f"lease:{self._lease_counter}"
        spec = {
            "kind": "lease",
            "resources": resources,
            "needs_tpu": bool(msg.get("needs_tpu")),
            "_conn": conn,
            "_mid": msg["_mid"],
            "_driver": conn.conn_id,
            "_lease_id": lease_id,
        }
        # In a multi-node cluster an unserved lease must fail fast so
        # the driver's daemon path can spill the work to other nodes;
        # single-node it waits (workers free up or spawn).
        multinode = (
            self.is_head
            and self.control is not None
            and len(self.control.nodes) > 1
        )
        if multinode:
            spec["_deadline"] = time.time() + 1.0
            timer = threading.Timer(1.1, self._expire_lease_requests)
            timer.daemon = True
            timer.start()
        self.scheduler.enqueue(lease_id, request, spec)
        self._schedule()
        return DEFERRED

    def _expire_lease_requests(self) -> None:
        now = time.time()
        expired = self.scheduler.drain_queued(
            lambda s: s.get("kind") == "lease"
            and s.get("_deadline") is not None
            and s["_deadline"] < now
        )
        for spec in expired:
            spec["_conn"].reply(spec["_mid"], {"unavailable": True})

    def _h_release_lease(self, conn, msg):
        self._release_lease(msg["lease_id"])
        return {}

    def _release_lease(self, lease_id: str) -> None:
        with self._lock:
            entry = self.leases.pop(lease_id, None)
            if entry is None:
                return
            worker_conn_id, _ = entry
            worker = self.workers.get(worker_conn_id)
            if worker is not None:
                worker.leased_by = None
                worker.current_task = None
                worker.idle = True
                worker.idle_since = time.time()
        self.scheduler.release(lease_id)
        self._schedule()

    def _release_driver_leases(self, driver_conn_id: int) -> None:
        """Driver disconnected: return its leased workers and drop its
        queued lease requests."""
        with self._lock:
            held = [
                lid for lid, (_, drv) in self.leases.items()
                if drv == driver_conn_id
            ]
        for lid in held:
            self._release_lease(lid)
        dropped = self.scheduler.drain_queued(
            lambda s: s.get("kind") == "lease"
            and s.get("_driver") == driver_conn_id
        )
        if dropped:
            self._schedule()

    def _h_actor_address(self, conn, msg):
        """Resolve an actor's direct endpoint; defers until the actor
        leaves PENDING/RESTARTING. Empty reply = use the daemon path
        (remote node, dead, or no direct endpoint)."""
        if not self.is_head:
            # Never proxy: the head defers this reply until the actor
            # is ALIVE, and a blocking head.call here would wedge this
            # connection's dispatch thread (all RPC from that client)
            # behind actor creation. Drivers attach to the head, so a
            # request here is out of contract — daemon path.
            return {}
        actor_id = ActorID(msg["actor_id"])
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None or runtime.info.state == ACTOR_DEAD:
                return {}
            if runtime.info.state == ACTOR_ALIVE:
                return self._actor_address_reply(actor_id, runtime)
            self._actor_addr_waiters.setdefault(actor_id, []).append(
                (conn, msg["_mid"])
            )
        return DEFERRED

    def _actor_address_reply(self, actor_id, runtime) -> dict:
        """Caller holds the lock. ALIVE actor -> direct address if it
        is hosted by a local worker with an endpoint."""
        if runtime.node != self.node_id.binary():
            return {}
        host = self.actor_hosts.get(actor_id)
        if host is None or host.worker_conn_id is None:
            return {}
        worker = self.workers.get(host.worker_conn_id)
        if worker is None or not worker.direct_address:
            return {}
        return {
            "address": worker.direct_address,
            "worker_id": worker.worker_id.binary(),
        }

    def _wake_actor_addr_waiters(self, actor_id: ActorID) -> None:
        with self._lock:
            waiters = self._actor_addr_waiters.pop(actor_id, [])
            if not waiters:
                return
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None or runtime.info.state != ACTOR_ALIVE:
                reply = {}
            else:
                reply = self._actor_address_reply(actor_id, runtime)
        for conn, mid in waiters:
            conn.reply(mid, reply)

    # ------------------------------------------------------------------
    # node clients (head->node forwards, node->node pulls)
    # ------------------------------------------------------------------
    def _node_client(self, node_id: bytes) -> Optional[RpcClient]:
        with self._lock:
            client = self._node_clients.get(node_id)
        if client is not None:
            return client
        info = self.control.nodes.get(NodeID(node_id))
        if info is None or not info.alive:
            return None
        client = RpcClient(info.address)
        with self._lock:
            self._node_clients[node_id] = client
        return client

    def _peer_client(self, address: str) -> RpcClient:
        with self._lock:
            client = self._peer_clients.get(address)
        if client is None:
            client = RpcClient(address)
            with self._lock:
                self._peer_clients[address] = client
        return client

    # ------------------------------------------------------------------
    # KV (function/actor-class blobs — reference: GcsKvManager +
    # function_manager.py export/fetch protocol)
    # ------------------------------------------------------------------
    def _h_kv_put(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "kv_put", ns=msg.get("ns", ""), key=msg["key"],
                value=msg["value"], overwrite=msg.get("overwrite", True),
            )
        added = self.control.kv_put(
            msg.get("ns", ""), msg["key"], msg["value"],
            overwrite=msg.get("overwrite", True),
        )
        return {"added": added}

    def _h_kv_get(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "kv_get", ns=msg.get("ns", ""), key=msg["key"]
            )
        return {"value": self.control.kv_get(msg.get("ns", ""), msg["key"])}

    def _h_kv_del(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "kv_del", ns=msg.get("ns", ""), key=msg["key"]
            )
        self.control.kv_del(msg.get("ns", ""), msg["key"])
        return {}

    def _h_kv_keys(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "kv_keys", ns=msg.get("ns", ""),
                prefix=msg.get("prefix", ""),
            )
        return {
            "keys": self.control.kv_keys(
                msg.get("ns", ""), msg.get("prefix", "")
            )
        }

    # ------------------------------------------------------------------
    # objects — metadata (head) + local data plane (all nodes)
    # ------------------------------------------------------------------
    def _ensure_entry(self, oid: ObjectID) -> ObjectEntry:
        entry = self.objects.get(oid)
        if entry is None:
            entry = ObjectEntry()
            self.objects[oid] = entry
        return entry

    def _h_put_inline(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "put_inline", oid=msg["oid"], data=msg["data"],
                **self._owner_fwd(msg),
            )
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.inline = msg["data"]
            entry.size = len(msg["data"])
            entry.state = SEALED
            self._record_owner(entry, msg, local_pid=False)
        self._wake(oid)
        self._schedule()
        return {}

    @staticmethod
    def _record_owner(
        entry: ObjectEntry, msg: dict, local_pid: bool
    ) -> None:
        """Adopt owner attribution from a seal/put report (caller
        holds the lock). First writer wins — a secondary copy's seal
        must not re-attribute the object — and the owner pid is only
        meaningful where the creating client actually runs
        (`local_pid`: the node that took the client's own report)."""
        if msg.get("owner_job") and not entry.owner_job:
            entry.owner_job = str(msg["owner_job"])
            entry.owner = str(msg.get("owner", "") or "")
            if local_pid:
                entry.owner_pid = int(msg.get("owner_pid") or 0)
        if not entry.created_ts:
            # A pulled secondary copy inherits the primary's creation
            # time (leak age anchors at first seal, not local arrival).
            entry.created_ts = float(
                msg.get("created_ts") or 0.0
            ) or time.time()

    @staticmethod
    def _owner_fwd(msg: dict) -> dict:
        """Owner-attribution fields of a seal/put report, for
        forwarding to the head."""
        return {
            k: msg[k]
            for k in ("owner_job", "owner", "owner_pid")
            if k in msg
        }

    def _h_object_sealed(self, conn, msg):
        """A shm object was sealed. From a local worker: record the
        local copy (and, on worker nodes, tell the head). From a node
        daemon (head only): record the remote location."""
        oid = ObjectID(msg["oid"])
        source_node = msg.get("node_id")  # set when a node reports
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.size = msg["size"]
            entry.state = SEALED
            # Owner pid liveness is only probeable on the node the
            # creating client runs on — the node taking its direct
            # report (the head's directory copy keeps job/owner for
            # attribution, without the pid).
            self._record_owner(
                entry, msg, local_pid=source_node is None
            )
            if source_node is None:
                entry.in_shm = True  # sealed by a local client
            if self.is_head:
                entry.locations.add(source_node or self.node_id.binary())
        if source_node is None:
            # Primary copy: pin against eviction until spilled/deleted.
            self._pin_primary(oid, msg["size"])
        if not self.is_head and source_node is None:
            # Report our copy (with its attribution) to the head's
            # object directory.
            self.head.call(
                "object_sealed", oid=msg["oid"], size=msg["size"],
                node_id=self.node_id.binary(), **self._owner_fwd(msg),
            )
        self._wake(oid)
        self._schedule()
        return {}

    def _h_seal_error(self, conn, msg):
        if not self.is_head:
            reply = self.head.call(
                "seal_error", oid=msg["oid"], error=msg["error"]
            )
            # Also fail local waiters (workers blocked on this node).
            self._seal_error_local(ObjectID(msg["oid"]), msg["error"])
            self._schedule()  # errored deps count as resolved
            return reply
        self._seal_error_local(ObjectID(msg["oid"]), msg["error"])
        self._schedule()
        return {}

    def _seal_error(self, oid: ObjectID, error: bytes) -> None:
        """Mark an object as errored in the authoritative table."""
        if not self.is_head:
            try:
                self.head.call(
                    "seal_error", oid=oid.binary(), error=error
                )
            except RpcError:
                pass
        self._seal_error_local(oid, error)

    def _seal_error_local(self, oid: ObjectID, error: bytes) -> None:
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.error = error
            entry.state = ERRORED
        self._wake(oid)

    def _object_reply_local(self, oid: ObjectID) -> Optional[dict]:
        """Reply for a local consumer, or None if data must be pulled."""
        with self._lock:
            entry = self.objects.get(oid)
            if entry is None or entry.state == PENDING:
                return {"pending": True}
            if entry.state == ERRORED:
                return {"error": entry.error}
            if entry.inline is not None:
                return {"inline": entry.inline}
            if entry.in_shm:
                reply = {"shm_size": entry.size}
                if entry.source_fresh and entry.source:
                    # Provenance rides the reply only while fresh so
                    # the worker can classify this get's wait; the
                    # flag clears once the materialising event's
                    # waiters have been answered.
                    reply["via"] = entry.source
                    if entry.src_node:
                        reply["src"] = entry.src_node
                return reply
        return None  # sealed, data elsewhere

    def _wake(self, oid: ObjectID) -> None:
        """Wake waiters that can now be answered; re-arm data waiters
        whose object is sealed but remote (pull in progress)."""
        with self._lock:
            entry = self.objects.get(oid)
            if entry is None:
                return
            waiters = entry.waiters
            entry.waiters = []
            meta_waiters = entry.meta_waiters
            entry.meta_waiters = []
        for conn, mid in meta_waiters:
            conn.reply(mid, self._meta_reply(oid))
        needs_pull = False
        for conn, mid in waiters:
            reply = self._object_reply_local(oid)
            if reply is None:
                with self._lock:
                    entry.waiters.append((conn, mid))
                needs_pull = True
            else:
                conn.reply(mid, reply)
        if needs_pull:
            self._ensure_local(oid)

    def _h_get_object(self, conn, msg):
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            if entry.state == PENDING:
                entry.waiters.append((conn, msg["_mid"]))
                if not self.is_head:
                    pull_needed = not entry.pulling
                else:
                    pull_needed = False
            else:
                pull_needed = False
        if pull_needed:
            # On worker nodes PENDING may just mean "not local yet":
            # ask the head (blocks until sealed) then pull.
            self._ensure_local(oid)
            return DEFERRED
        if entry.state == PENDING:
            return DEFERRED
        reply = self._object_reply_local(oid)
        if reply is None:
            with self._lock:
                entry.waiters.append((conn, msg["_mid"]))
            self._ensure_local(oid)
            return DEFERRED
        return reply

    def _h_get_objects(self, conn, msg):
        """Batched NON-BLOCKING get: one round trip resolves every oid
        the daemon can answer right now (the worker's many-arg fetch
        path — per-arg blocking gets cost one RTT each). Unready or
        remote oids come back as pending markers (a pull is kicked for
        sealed-elsewhere entries); the caller falls back to blocking
        get_object for those, which waits exactly like before."""
        out = []
        pulls = []
        oids = msg["oids"]
        # Chunked lock scope: a 10k-oid request must not pin the hot
        # lock for the whole scan.
        for start in range(0, len(oids), 512):
            with self._lock:
                for blob in oids[start:start + 512]:
                    oid = ObjectID(blob)
                    entry = self.objects.get(oid)
                    if entry is None or entry.state == PENDING:
                        out.append({"pending": True})
                    elif entry.state == ERRORED:
                        out.append({"error": entry.error})
                    elif entry.inline is not None:
                        out.append({"inline": entry.inline})
                    elif entry.in_shm:
                        reply = {"shm_size": entry.size}
                        if entry.source_fresh and entry.source:
                            reply["via"] = entry.source
                            if entry.src_node:
                                reply["src"] = entry.src_node
                        out.append(reply)
                    else:
                        pulls.append(oid)
                        out.append({"pending": True})
        for oid in pulls:
            self._ensure_local(oid)
        return {"results": out}

    def _meta_reply(self, oid: ObjectID) -> dict:
        """Metadata view served to node daemons (head only)."""
        with self._lock:
            entry = self.objects.get(oid)
            if entry is None or entry.state == PENDING:
                return {"pending": True}
            if entry.state == ERRORED:
                return {"error": entry.error}
            if entry.inline is not None:
                return {"inline": entry.inline}
            locations = []
            for nid in entry.locations:
                info = self.control.nodes.get(NodeID(nid))
                if info is not None and info.alive:
                    locations.append((nid, info.address))
            # Attribution rides the meta so a pulling node's secondary
            # copy lands in its arena already attributed (no pid: the
            # creator doesn't run there, liveness is unknowable).
            return {
                "size": entry.size,
                "locations": locations,
                "owner_job": entry.owner_job,
                "owner": entry.owner,
                "created_ts": entry.created_ts,
            }

    def _h_get_object_meta(self, conn, msg):
        oid = ObjectID(msg["oid"])
        with self._lock:
            entry = self._ensure_entry(oid)
            if entry.state == PENDING:
                entry.meta_waiters.append((conn, msg["_mid"]))
                return DEFERRED
        reply = self._meta_reply(oid)
        if reply.get("size") is not None and not reply["locations"]:
            # All copies lost: try lineage reconstruction, keep waiting.
            with self._lock:
                entry.meta_waiters.append((conn, msg["_mid"]))
            self._maybe_reconstruct(oid)
            return DEFERRED
        return reply

    def _h_pull_object(self, conn, msg):
        """Serve a chunk of a locally-stored object (reference:
        PushManager chunking, object_manager/push_manager.h)."""
        oid = ObjectID(msg["oid"])
        self.core_counters.bump("pushes")
        offset = msg.get("offset", 0)
        length = msg.get("length", self.config.object_transfer_chunk_size)
        with self._lock:
            entry = self.objects.get(oid)
            size = entry.size if entry is not None and entry.in_shm else None
        if getattr(self.store, "needs_release", False):
            pin = self.store.acquire(oid, timeout=0.1)
            if pin is None:
                return self._pull_from_spill(oid, offset, length)
            try:
                total = len(pin.view)
                view = pin.view[offset : min(offset + length, total)]
                # Zero-copy send: reply INSIDE the pin scope so the
                # chunk scatter-gathers straight from the arena onto
                # the socket (pickle-5 out-of-band buffer) — the
                # bytes() staging copy this replaces was one full
                # memcpy per transferred chunk. sendmsg has fully
                # handed the bytes to the kernel when reply returns,
                # so releasing the pin afterwards is safe.
                conn.reply(
                    msg["_mid"],
                    {"data": _oob_chunk(view), "total_size": total},
                )
                return DEFERRED
            finally:
                pin.release()
        view = self.store.get(oid, timeout=0.1)
        if view is None and size is not None:
            # Segment was created directly by a local worker process;
            # attach by name (plasma clients mmap by object id).
            try:
                view = self.store.open_remote(oid, size)
            except FileNotFoundError:
                view = None
        if view is None:
            return self._pull_from_spill(oid, offset, length)
        total = len(view)
        # Zero-copy: the numpy wrapper keeps the segment view (and its
        # pages) alive until the reply frame has been sent; per-object
        # segments are kernel-refcounted, so a concurrent delete only
        # unlinks the name.
        chunk = view[offset : min(offset + length, total)]
        return {"data": _oob_chunk(chunk), "total_size": total}

    def _pull_from_spill(self, oid: ObjectID, offset: int, length: int):
        """Serve a pull chunk straight from this node's spill file —
        remote reads need not restore the shm copy first."""
        if self.spill is not None and self.spill.contains(oid):
            data = self.spill.read(oid, offset, length)
            total = self.spill.size(oid)
            if data is not None and total is not None:
                # Marker lets the puller classify the transfer as a
                # remote spill restore rather than an arena pull.
                return {
                    "data": _oob_chunk(data),
                    "total_size": total,
                    "from_spill": True,
                }
        return {"missing": True}

    def _h_delete_object(self, conn, msg):
        """Head tells this node to drop its copy (refcount hit zero)."""
        oid = ObjectID(msg["oid"])
        with self._lock:
            self.objects.pop(oid, None)
        self._drop_local_copy(oid)
        return {}

    def _drop_local_copy(self, oid: ObjectID, in_shm: bool = True) -> None:
        """Release every local holding of one object: the primary pin,
        the shm segment (unlink_by_id also reaches segments created
        directly by local worker processes — the daemon never attached
        them), and any spill file."""
        self._unpin_primary(oid)
        if in_shm:
            self.store.unlink_by_id(oid)
        else:
            self.store.delete(oid)
        if self.spill is not None:
            self.spill.delete(oid)

    def _h_object_evicted(self, conn, msg):
        """A node evicted a cached copy under memory pressure — or, in
        the native-arena store, a local worker process whose create()
        triggered eviction (no node_id in that case)."""
        oid = ObjectID(msg["oid"])
        node_id = msg.get("node_id")
        if node_id is None:
            # Local worker eviction: shared arena means this node's
            # copy is gone; run the node-level eviction path.
            self._on_store_evict(oid)
            return {}
        with self._lock:
            entry = self.objects.get(oid)
            if entry is not None:
                entry.locations.discard(node_id)
        return {}

    def _on_store_evict(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self.objects.get(oid)
            if entry is not None:
                entry.in_shm = False
                if entry.spilled:
                    # The spill file still serves this object from this
                    # node — keep the directory location alive.
                    return
        if self.is_head:
            with self._lock:
                if entry is not None:
                    entry.locations.discard(self.node_id.binary())
        elif self.head is not None:
            try:
                self.head.notify(
                    "object_evicted", oid=oid.binary(),
                    node_id=self.node_id.binary(),
                )
            except Exception:
                pass

    # ------------------------------------------------------------------
    # object spilling (reference: raylet LocalObjectManager,
    # local_object_manager.h:110 SpillObjectsOfSize; restore path
    # AsyncRestoreSpilledObject; storage external_storage.py:72)
    # ------------------------------------------------------------------
    _PIN_ABSENT = object()

    def _pin_primary(
        self, oid: ObjectID, size: int, pin=None
    ) -> None:
        """Pin a locally-sealed (primary) copy against eviction.
        `pin` carries a ready ArenaPin taken atomically at seal time
        (seal_pinned) — adopted instead of acquiring a fresh one.

        Entry protocol for self._primary_pins[oid]:
          absent       — unprotected
          None         — reservation: some thread is acquiring a pin
          pin object   — protected
        A ready pin FILLS a pending reservation (releasing it there
        would reopen the zero-pin eviction window while the reserver
        is still acquiring); the reserver only installs its own pin if
        the entry is still its empty reservation, else releases it.
        """
        with self._lock:
            existing = self._primary_pins.get(oid, self._PIN_ABSENT)
            if existing is None:
                # Pending reservation from another thread.
                if pin is not None:
                    self._primary_pins[oid] = pin  # fill it
                return  # (reserver will see the fill and stand down)
            if existing is not self._PIN_ABSENT:
                if pin is not None:
                    self._release_pin(pin)  # truly already protected
                return
            self._primary_pins[oid] = pin  # pin, or None = reservation
            if pin is not None:
                return
        # We hold the empty reservation: acquire outside the lock.
        if getattr(self.store, "needs_release", False):
            pin = self.store.acquire(oid, timeout=0)
        else:
            if not self.store.contains(oid):
                try:
                    self.store.open_remote(oid, size)
                except FileNotFoundError:
                    with self._lock:
                        if self._primary_pins.get(oid) is None:
                            self._primary_pins.pop(oid, None)
                    return
            self.store.pin(oid)
            pin = oid  # marker: pinned in the py store
        stale = False
        with self._lock:
            current = self._primary_pins.get(oid, self._PIN_ABSENT)
            if current is None:
                # Still our empty reservation.
                if pin is None:
                    self._primary_pins.pop(oid, None)
                else:
                    self._primary_pins[oid] = pin
            else:
                # Deleted concurrently (absent) or a seal-time pin
                # filled the reservation first — our pin is surplus.
                stale = True
        if stale and pin is not None:
            self._release_pin(pin)

    def _release_pin(self, pin) -> None:
        if getattr(self.store, "needs_release", False):
            try:
                pin.release()
            except Exception:
                pass
        else:
            self.store.unpin(pin)  # py-store marker IS the oid

    def _unpin_primary(self, oid: ObjectID) -> None:
        with self._lock:
            pin = self._primary_pins.pop(oid, None)
        if pin is None:
            return
        if getattr(self.store, "needs_release", False):
            try:
                pin.release()
            except Exception:
                pass
        else:
            self.store.unpin(oid)

    # ------------------------------------------------------------------
    # log streaming (reference: _private/log_monitor.py — tail worker
    # log files, publish line batches; driver prints with prefixes)
    # ------------------------------------------------------------------
    def _on_head_push(self, channel: str, msg: dict) -> None:
        """Pushes arriving on the node->head client connection: relayed
        pubsub events (log batches, error events, future channels) for
        this node's local subscribers."""
        if channel:
            msg = {
                k: v for k, v in msg.items()
                if k not in ("_mid", "_push")
            }
            self._push_to_subscribers(channel, msg)

    def _on_head_reconnect(self) -> None:
        """Per-connection head state must be re-established after a
        transparent RpcClient reconnect."""
        with self._lock:
            has_subs = bool(self._log_subscribers)
        if has_subs:
            self._ensure_log_relay()

    def _h_subscribe_logs(self, conn, msg):
        """Subscribe this connection to pushed pubsub channels
        ("log_lines" worker output, "error_event" cluster failures).
        The conn may be a local driver OR (on the head) a worker-node
        daemon relaying for its own local drivers."""
        channels = set(msg.get("channels") or ("log_lines",))
        with self._lock:
            prev = self._log_subscribers.get(conn.conn_id)
            if prev is not None:
                channels |= prev[1]
            self._log_subscribers[conn.conn_id] = (conn, channels)
        if not self.is_head and self.head is not None:
            # Relay: all events flow through the head (every node
            # forwards there), so a driver attached to a non-head node
            # sees cluster-wide traffic by this node subscribing
            # upstream for the union of its local channels.
            self._ensure_log_relay()
        return {}

    def _ensure_log_relay(self) -> None:
        with self._lock:
            union = set()
            for _, chans in self._log_subscribers.values():
                union |= chans
        try:
            self.head.notify("subscribe_logs", channels=sorted(union))
        except Exception:
            pass

    def _h_unsubscribe_logs(self, conn, msg):
        self._drop_log_subscriber(conn.conn_id)
        return {}

    def _drop_log_subscriber(self, conn_id: int) -> None:
        """Remove one subscriber; when a relay node's LAST local
        subscriber goes, tear the upstream relay down too — otherwise
        one past driver session would keep the whole cluster tailing
        and forwarding forever."""
        with self._lock:
            was_sub = self._log_subscribers.pop(conn_id, None) is not None
            any_left = bool(self._log_subscribers)
        if (
            was_sub
            and not any_left
            and not self.is_head
            and self.head is not None
        ):
            try:
                self.head.notify("unsubscribe_logs")
            except Exception:
                pass

    def _h_log_batch(self, conn, msg):
        """A worker node forwards its tailed log lines (head only)."""
        self._push_logs(msg["batches"], msg.get("node", ""))
        return {}

    def _h_publish_event(self, conn, msg):
        """A worker node forwards a pubsub event for head fan-out."""
        self._push_to_subscribers(msg["channel"], msg["payload"])
        return {}

    def _push_to_subscribers(self, channel: str, payload: dict) -> None:
        """Fan one event out to every subscriber of `channel` (shared
        by log batches and error events; pop subscribers whose
        connection died)."""
        with self._lock:
            subs = [
                (cid, conn)
                for cid, (conn, chans) in self._log_subscribers.items()
                if channel in chans
            ]
        for conn_id, conn in subs:
            try:
                conn.push(channel, payload)
            except Exception:
                with self._lock:
                    self._log_subscribers.pop(conn_id, None)

    def _push_logs(self, batches: list, node: str) -> None:
        # Known limitation vs the reference's per-job log_monitor
        # filtering: workers here are shared across jobs, so a stdout
        # line has no reliable job attribution — every subscriber gets
        # every line (prefixed by worker/pid/node). Multi-driver
        # sessions wanting isolation set log_to_driver=False and read
        # session-dir files.
        self._push_to_subscribers(
            "log_lines", {"batches": batches, "node": node}
        )

    def _logs_wanted(self) -> bool:
        """Whether anyone, anywhere, wants this node's log lines."""
        with self._lock:
            local = any(
                "log_lines" in chans
                for _, chans in self._log_subscribers.values()
            )
        if local:
            return True
        # Worker nodes learn via the heartbeat reply whether the head
        # has subscribers (drivers or node relays).
        return (not self.is_head) and self._head_logs_wanted

    def _log_monitor_loop(self) -> None:
        offsets: Dict[str, int] = {}
        node_hex = self.node_id.hex()[:8]
        while not self._shutdown:
            try:
                if not self._logs_wanted():
                    # Nobody listening: skip the tail work but keep
                    # offsets at EOF so a new subscriber gets a live
                    # stream, not a history dump.
                    self._fast_forward_logs(offsets)
                else:
                    batches = self._tail_worker_logs(offsets)
                    if batches:
                        if self.is_head:
                            self._push_logs(batches, node_hex)
                        elif self.head is not None:
                            # Single path: batches go up to the head,
                            # which fans out to drivers and node
                            # relays (including back to this node if a
                            # local driver subscribed) — no double
                            # delivery.
                            self.head.notify(
                                "log_batch", batches=batches,
                                node=node_hex,
                            )
            except Exception:
                pass
            time.sleep(self.config.log_monitor_interval_s)

    def _fast_forward_logs(self, offsets: Dict[str, int]) -> None:
        for i in range(len(self._worker_procs)):
            path = os.path.join(self.session_dir, f"worker-{i}.out")
            try:
                offsets[path] = os.path.getsize(path)
            except OSError:
                pass

    def _tail_worker_logs(self, offsets: Dict[str, int]) -> list:
        """Read complete new lines from each worker's log file."""
        batches = []
        for i, proc in enumerate(list(self._worker_procs)):
            path = os.path.join(self.session_dir, f"worker-{i}.out")
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = offsets.get(path, 0)
            if size <= off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(min(size - off, 256 * 1024))
            except OSError:
                continue
            nl = data.rfind(b"\n")
            if nl < 0:
                # No complete line yet; flush anyway if the partial
                # line is absurdly long so progress can't stall.
                if len(data) < 64 * 1024:
                    continue
                nl = len(data) - 1
            chunk, consumed = data[: nl + 1], nl + 1
            offsets[path] = off + consumed
            batches.append({
                "worker": i,
                "pid": proc.pid,
                "lines": chunk.decode(errors="replace").splitlines(),
            })
        return batches

    def _maintenance_loop(self) -> None:
        """Periodic store upkeep on EVERY daemon (the head included —
        worker nodes additionally reap via their heartbeat loop):
        reclaim arena pins of crashed/killed reader processes, then
        spill under pressure. A dead reader's pin otherwise defers
        deletion forever and leaks the slot."""
        while not self._shutdown:
            # Reap zombie worker children FIRST: a SIGKILLed worker
            # stays a zombie until waitpid, and the arena's pid-liveness
            # check (kill(pid, 0)) reports zombies as alive — its pins
            # would defer slot frees forever.
            for proc in list(self._worker_procs):
                try:
                    proc.poll()
                except Exception:
                    pass
            reap = getattr(self.store, "reap_dead_pins", None)
            if reap is not None:
                try:
                    reap()
                except Exception:
                    pass
            try:
                self._maybe_spill()
            except Exception:
                pass
            try:
                self._reap_idle_workers()
            except Exception:
                pass
            time.sleep(self.config.object_eviction_check_interval_s)

    #: Idle workers beyond the pool cap live this long before exiting.
    _IDLE_WORKER_GRACE_S = 5.0

    def _reap_idle_workers(self) -> None:
        """Shrink the warm pool back to worker_pool_max_idle_workers
        (reference: WorkerPool TryKillingIdleWorkers,
        worker_pool.cc — idle workers past the cap are asked to exit
        after a grace period). Leased and actor-pinned workers never
        count as idle."""
        cap = self.config.worker_pool_max_idle_workers or max(
            1, int(self.resources.get("CPU", 1))
        )
        now = time.time()
        with self._lock:
            idle = [
                w for w in self.workers.values()
                if w.idle
                and w.pinned_actor is None
                and w.leased_by is None
            ]
            excess = len(idle) - cap
            if excess <= 0:
                return
            idle.sort(key=lambda w: w.idle_since)  # oldest first
            victims = [
                w for w in idle[:excess]
                if now - w.idle_since > self._IDLE_WORKER_GRACE_S
            ]
            for w in victims:
                # Unschedulable from the same critical section that
                # selected it: a dispatch racing the exit push would
                # otherwise land a task on a dying worker and surface
                # a spurious WorkerCrashedError.
                w.idle = False
        for w in victims:
            try:
                w.conn.push("exit", {})
            except Exception:
                pass

    def _h_spill_request(self, conn, msg):
        """A local worker hit store-full on create: synchronously free
        space by spilling (reference: plasma's create retries after the
        raylet spills, create_request_queue.h)."""
        freed = self._maybe_spill(bytes_needed=msg.get("bytes_needed", 0))
        return {"freed": freed}

    def _maybe_spill(self, bytes_needed: int = 0) -> int:
        """Spill LRU sealed objects until store usage is back under the
        spilling threshold (plus `bytes_needed` headroom). Returns the
        number of bytes freed from the store."""
        if self.spill is None:
            return 0
        with self._spill_lock:
            info = self.store.size_info()
            high = self.config.object_spilling_threshold * info["capacity"]
            target = info["used"] + bytes_needed - high
            if target <= 0:
                return 0
            with self._lock:
                # Insertion order approximates LRU: oldest sealed local
                # objects first. Inline and unsealed objects are not
                # spillable; errored ones have no data.
                victims = [
                    (oid, e.size)
                    for oid, e in self.objects.items()
                    if e.in_shm and e.state == SEALED and e.inline is None
                ]
            freed = 0
            for oid, size in victims:
                if freed >= target:
                    break
                if self._spill_one(oid, size):
                    freed += size
            return freed

    def _spill_one(self, oid: ObjectID, size: int) -> bool:
        """Write one object's bytes to spill storage, then drop its shm
        copy. The head keeps this node in the object's location set —
        the spill file serves pulls and restores."""
        try:
            if getattr(self.store, "needs_release", False):
                pin = self.store.acquire(oid, timeout=0)
                if pin is None:
                    return False
                try:
                    self.spill.spill(oid, pin.view)
                finally:
                    pin.release()
            else:
                view = self.store.get(oid, timeout=0)
                if view is None:
                    # Segment created by a local worker process; attach.
                    try:
                        view = self.store.open_remote(oid, size)
                    except FileNotFoundError:
                        return False
                self.spill.spill(oid, view)
        except Exception:
            return False
        with self._lock:
            entry = self.objects.get(oid)
            if entry is None:
                # Deleted concurrently; drop the orphan file.
                self.spill.delete(oid)
                return False
            entry.spilled = True
            entry.in_shm = False
            job = entry.owner_job
        self._unpin_primary(oid)
        self.store.unlink_by_id(oid)
        self.core_counters.bump("spills")
        self._bump_job_op(self._job_spill_ops, job)
        return True

    def _bump_job_op(self, table: Dict[str, int], job: str) -> None:
        """Count one spill/restore op against a job on THIS node
        (""-keyed when unattributed); cumulative, shipped with the
        node memory report."""
        with self._lock:
            table[job] = table.get(job, 0) + 1

    def _report_transfer(
        self, job: str, src: str, kind: str, nbytes: int, ms: float
    ) -> None:
        """Bill one completed (or aborted) data movement INTO this
        node against the (job, src, dst) flow. Rides the metrics pipe
        like step records — the head folds its own directly, a worker
        node piggybacks one notify per pull/restore OP (never one per
        get; gets aggregate worker-side)."""
        if self.config.transfer_report_interval_s <= 0:
            return
        rec = (
            "transfer",
            kind,
            float(nbytes),
            (
                ("dst", self.node_id.hex()),
                ("job", job or ""),
                ("ms", str(round(ms, 3))),
                ("src", src),
            ),
        )
        if self.is_head:
            with self._lock:
                self._apply_metric_record(rec)
        elif self.head is not None:
            try:
                # No (sender, seq): a lost notify costs one record,
                # not a double-count — transfer ops are rare enough
                # (per pull, not per get) that dedup bookkeeping
                # isn't worth a synchronous call on the pull path.
                self.head.notify("metrics_record", records=[rec])
            except Exception:
                pass

    def _restore_spilled(self, oid: ObjectID) -> bool:
        """Copy a spilled object back into the shm store so local
        consumers map it zero-copy again."""
        if self.spill is None:
            return False
        t0 = time.perf_counter()
        data = self.spill.read(oid)
        if data is None:
            return False
        pin = None

        def _put_pinned():
            # seal_pinned (arena) closes the window where the restored
            # copy is sealed but not yet primary-pinned and a foreign
            # create() LRU-evicts it again.
            buf = self.store.create(oid, len(data))
            buf[: len(data)] = data
            seal_pinned = getattr(self.store, "seal_pinned", None)
            if seal_pinned is not None:
                return seal_pinned(oid)
            self.store.seal(oid)
            return None

        try:
            try:
                pin = _put_pinned()
            except ObjectStoreFullError:
                # Make room by spilling colder objects, then retry once.
                self._maybe_spill(bytes_needed=len(data))
                pin = _put_pinned()
        except ValueError:
            pass  # already (re-)created by a concurrent restore
        except ObjectStoreFullError:
            return False
        with self._lock:
            entry = self._ensure_entry(oid)
            entry.in_shm = True
            entry.size = len(data)
            entry.state = SEALED
            # Provenance: waiters woken by this restore classify their
            # wait as a spill restore, not an arena hit.
            entry.source = "restore"
            entry.src_node = ""
            entry.source_fresh = True
            job = entry.owner_job
            if self.is_head:
                entry.locations.add(self.node_id.binary())
        self._pin_primary(oid, len(data), pin=pin)
        self.core_counters.bump("restores")
        self._bump_job_op(self._job_restore_ops, job)
        self._report_transfer(
            job, self.node_id.hex(), "restore", len(data),
            (time.perf_counter() - t0) * 1000.0,
        )
        return True

    # -- cross-node pull -------------------------------------------------
    def _ensure_local(self, oid: ObjectID) -> None:
        """Asynchronously make a sealed object's data local to this
        node (reference: PullManager, object_manager/pull_manager.h)."""
        with self._lock:
            entry = self._ensure_entry(oid)
            if entry.pulling or entry.in_shm or entry.inline is not None:
                return
            if entry.state == ERRORED:
                return
            entry.pulling = True
        threading.Thread(
            target=self._pull_worker, args=(oid,), daemon=True,
            name=f"pull:{oid.hex()[:8]}",
        ).start()

    def _pull_worker(self, oid: ObjectID) -> None:
        try:
            self._pull_once(oid)
        finally:
            with self._lock:
                entry = self.objects.get(oid)
                if entry is not None:
                    entry.pulling = False
            self._wake(oid)
            # Waiters answered: later gets of the (now warm) copy are
            # plain local arena hits, not pull/restore waits.
            with self._lock:
                entry = self.objects.get(oid)
                if entry is not None:
                    entry.source_fresh = False
            self._schedule()

    def _pull_once(self, oid: ObjectID) -> None:
        # Restore-from-spill fast path: the data never left this node's
        # disk (reference: AsyncRestoreSpilledObject before remote pull,
        # local_object_manager.h).
        if (
            self.spill is not None
            and self.spill.contains(oid)
            and self._restore_spilled(oid)
        ):
            return
        for attempt in range(5):
            if self.is_head:
                meta = self._meta_reply(oid)
            else:
                try:
                    # retries: a transiently dropped meta RPC (chaos
                    # injection, head failover blip) must not abandon
                    # the pull — nothing re-arms it until an unrelated
                    # seal event.
                    meta = self.head.call(
                        "get_object_meta", oid=oid.binary(), retries=3
                    )
                except RpcError:
                    return
            if meta.get("error") is not None:
                self._seal_error_local(oid, meta["error"])
                return
            if meta.get("inline") is not None:
                with self._lock:
                    entry = self._ensure_entry(oid)
                    entry.inline = meta["inline"]
                    entry.size = len(meta["inline"])
                    entry.state = SEALED
                return
            if meta.get("pending"):
                # Head path only (node meta call blocks until sealed):
                # object not produced yet; waiters stay armed.
                return
            size = meta["size"]
            locations = [
                (nid, addr)
                for nid, addr in meta["locations"]
                if nid != self.node_id.binary()
            ]
            if not locations:
                # A local spill file outranks reconstruction: an earlier
                # restore may have failed only because the store was
                # momentarily too full (finding: restore-fail must not
                # look like data loss while the bytes sit on this disk).
                if (
                    self.spill is not None
                    and self.spill.contains(oid)
                ):
                    if self._restore_spilled(oid):
                        return
                    time.sleep(0.2 * (attempt + 1))
                    continue
                if self.is_head:
                    self._maybe_reconstruct(oid)
                    return
                time.sleep(0.2 * (attempt + 1))
                continue
            # Random source among ALL copy holders: N nodes pulling the
            # same object spread across each other as copies appear
            # instead of serializing on the owner (reference intent:
            # PushManager broadcast; here an organic pull tree).
            import random as _random

            nid, addr = _random.choice(locations)
            t0 = time.perf_counter()
            from_spill = False
            if self._pull_same_host(nid, oid, size):
                pulled = True
            else:
                client = (
                    self._node_client(nid) if self.is_head
                    else self._peer_client(addr)
                )
                if client is None:
                    continue
                pulled, from_spill = self._pull_chunks(
                    client, oid, size
                )
            pull_ms = (time.perf_counter() - t0) * 1000.0
            src_hex = NodeID(nid).hex()
            if not pulled:
                # The aborted attempt is COUNTED against the flow but
                # its bytes are never billed as transferred (the
                # ledger's "aborted" kind only bumps the op count) —
                # a retry that succeeds bills the full size exactly
                # once.
                self._report_transfer(
                    meta.get("owner_job", ""), src_hex, "aborted",
                    size, pull_ms,
                )
            if pulled:
                # from_spill is None when no bytes actually moved (a
                # concurrent pull won the race) — the winner already
                # billed the transfer and stamped provenance.
                kind = "pull_spill" if from_spill else "pull"
                with self._lock:
                    entry = self._ensure_entry(oid)
                    entry.in_shm = True
                    entry.size = size
                    entry.state = SEALED
                    if from_spill is not None:
                        # Provenance for waiters: a spill-served pull
                        # is a remote restore, an arena-served one a
                        # plain pull.
                        entry.source = kind
                        entry.src_node = src_hex
                        entry.source_fresh = True
                    # The secondary copy fills THIS node's arena: carry
                    # the owner from the meta so the memory ledger can
                    # attribute the bytes here too.
                    self._record_owner(entry, meta, local_pid=False)
                    if self.is_head:
                        entry.locations.add(self.node_id.binary())
                if from_spill is not None:
                    self._report_transfer(
                        meta.get("owner_job", ""), src_hex, kind,
                        size, pull_ms,
                    )
                if not self.is_head:
                    try:
                        self.head.call(
                            "object_sealed", oid=oid.binary(), size=size,
                            node_id=self.node_id.binary(),
                        )
                    except RpcError:
                        pass
                return
        # Exhausted retries: leave waiters armed; a future seal or
        # location report re-wakes them.

    def _pull_same_host(
        self, src_nid: bytes, oid: ObjectID, size: int
    ) -> bool:
        """Same-host transfer: attach the source daemon's shared
        arena and copy the slot under a pin — one memcpy, no sockets
        (reference: plasma hands same-host clients the store mmap and
        only the object manager moves bytes over the network,
        object_manager/object_manager.h; two daemons on one host are
        'network peers' only in topology, not in memory). Falls back
        to chunked socket pulls when the source's arena file isn't on
        this machine, the store isn't the native arena, or the object
        vanished (eviction race)."""
        if not getattr(self.store, "needs_release", False):
            return False  # py store: per-object segments, socket path
        path = f"/dev/shm/rt_arena_{NodeID(src_nid).hex()[:8]}"
        if not os.path.exists(path):
            return False  # different host (or source gone)
        from .object_store import ArenaPin

        try:
            arena = self._peer_arenas.get(path)
            if arena is None:
                from .._native import NativeArena

                arena = NativeArena.attach(path)
                self._peer_arenas[path] = arena  # rt: noqa[RT201] — worst case is a duplicate NativeArena.attach of the same file (harmless); shutdown() only overlaps at process exit
            pinned = arena.try_pin(oid.binary())
        except Exception:
            return False
        if pinned is None:
            return False  # evicted at the source: retry via meta
        index, view = pinned
        pin = ArenaPin(arena, view, index)
        try:
            if len(view) != size:
                return False  # stale metadata; let the socket path sort it
            if self.store.contains(oid):
                return True
            try:
                buf = self.store.create(oid, size)
            except ValueError:
                return True  # concurrent pull won
            except Exception:
                return False
            buf[:size] = view
            self.store.seal(oid)
            return True
        finally:
            pin.release()

    def _pull_chunks(
        self, client: RpcClient, oid: ObjectID, size: int
    ) -> Tuple[bool, Optional[bool]]:
        """Transfer one object with a WINDOW of chunk requests in
        flight (reference: PushManager streams chunks concurrently
        under an in-flight cap, push_manager.h). The serial
        request-per-chunk loop this replaces was latency-bound: a
        cross-node 1 GiB transfer paid one RTT per 5 MiB.

        Returns ``(ok, from_spill)``; ``from_spill`` is True when the
        source served the bytes from its spill file rather than its
        arena, and None when no bytes moved at all (already local or a
        concurrent pull won) so the caller must not bill a transfer."""
        if self.store.contains(oid):
            return True, None
        chunk_size = self.config.object_transfer_chunk_size
        try:
            buf = self.store.create(oid, size)
        except ValueError:
            return True, None  # concurrent pull won
        except Exception:
            return False, None
        self.core_counters.bump("pulls")
        self.core_counters.bump(
            "pull_chunks", max(1, -(-size // chunk_size))
        )
        window = max(1, min(
            8,
            self.config.object_pull_max_bytes_in_flight // chunk_size,
        ))
        n_chunks = max(1, -(-size // chunk_size))
        lock = threading.Lock()
        done = threading.Event()
        state = {
            "next": 0, "inflight": 0, "completed": 0,
            "err": None, "aborted": False, "from_spill": False,
        }

        def plan_launches_locked() -> list:
            """Reserve the next chunk requests (caller holds lock)."""
            planned = []
            while (
                state["inflight"] < window
                and state["next"] < n_chunks
                and state["err"] is None
            ):
                idx = state["next"]
                state["next"] += 1
                state["inflight"] += 1
                off = idx * chunk_size
                planned.append((off, min(chunk_size, size - off)))
            return planned

        def issue(planned: list) -> None:
            # MUST run with the lock released: call_async invokes the
            # callback synchronously on this same thread when the
            # client is closed or the send hits ConnectionLost, and
            # the callback takes the (non-reentrant) lock.
            for off, length in planned:
                client.call_async(
                    "pull_object", _make_cb(off, length),
                    oid=oid.binary(), offset=off, length=length,
                )

        def _make_cb(off, length):
            def cb(reply):
                planned = []
                with lock:
                    state["inflight"] -= 1
                    if reply.get("from_spill"):
                        state["from_spill"] = True
                    if state["aborted"]:
                        pass  # buffer may already be gone; drop it
                    elif state["err"] is None:
                        data = reply.get("data")
                        if (
                            reply.get("_error")
                            or reply.get("missing")
                            or data is None
                            or len(data) == 0
                        ):
                            state["err"] = reply.get(
                                "_error", "source missing object/chunk"
                            )
                        elif len(data) != length:
                            # A short chunk means the source's copy
                            # disagrees with the metadata size; sealing
                            # would serve a zero-filled hole.
                            state["err"] = (
                                f"short chunk at {off}: "
                                f"{len(data)} != {length}"
                            )
                        else:
                            try:
                                buf[off : off + length] = data
                                state["completed"] += 1
                            except Exception as e:  # released buffer
                                state["err"] = str(e)
                    finished = state["completed"] == n_chunks
                    failed = (
                        state["err"] is not None
                        and state["inflight"] == 0
                    )
                    if finished or failed:
                        done.set()
                    elif state["err"] is None:
                        planned = plan_launches_locked()
                issue(planned)
            return cb

        with lock:
            first = plan_launches_locked()
        issue(first)
        # Overall deadline scales with size (floor 60s); a wedged
        # source fails the pull instead of hanging the waiter forever.
        deadline = 60.0 + size / (1 * 1024 * 1024)
        if not done.wait(timeout=deadline):
            with lock:
                state["err"] = "pull timed out"
                state["aborted"] = True
        ok = state["err"] is None and state["completed"] == n_chunks
        if not ok:
            with lock:
                state["aborted"] = True
            self.store.delete(oid)
            # Mid-flight death of the source (or eviction under it) is
            # counted distinctly; the caller reports the flow-level
            # "aborted" record (bytes never billed as transferred).
            self.core_counters.bump("pulls_aborted")
            return False, state["from_spill"]
        self.store.seal(oid)
        return True, state["from_spill"]

    # -- wait ------------------------------------------------------------
    def _h_wait_objects(self, conn, msg):
        if not self.is_head:
            mid = msg["_mid"]

            def proxy():
                try:
                    reply = self.head.call(
                        "wait_objects", oids=msg["oids"],
                        num_returns=msg["num_returns"],
                        wait_timeout=msg.get("wait_timeout"),
                    )
                except RpcError as e:
                    reply = {"_error": str(e)}
                conn.reply(mid, reply)

            threading.Thread(target=proxy, daemon=True).start()
            return DEFERRED
        oids = [ObjectID(b) for b in msg["oids"]]
        num_returns = msg["num_returns"]
        timeout = msg.get("wait_timeout")
        state = {"done": False}

        def check_and_reply(force: bool = False):
            with self._lock:
                if state["done"]:
                    return
                ready = [
                    o.binary()
                    for o in oids
                    if self.objects.get(o) is not None
                    and self.objects[o].state != PENDING
                ]
                if len(ready) >= num_returns or force:
                    state["done"] = True
                    remaining = [
                        o.binary() for o in oids if o.binary() not in set(ready)
                    ]
                    conn.reply(
                        msg["_mid"], {"ready": ready, "remaining": remaining}
                    )

        with self._lock:
            for o in oids:
                entry = self._ensure_entry(o)
                if entry.state == PENDING:
                    entry.waiters.append(
                        (_CallbackConn(check_and_reply), None)
                    )
        if timeout is not None:
            threading.Timer(timeout, lambda: check_and_reply(force=True)).start()
        check_and_reply()
        return DEFERRED

    # -- refcounting -----------------------------------------------------
    def _h_add_ref(self, conn, msg):
        if not self.is_head:
            self.head.notify("add_ref", oids=msg["oids"])
            return {}
        with self._lock:
            for b in msg["oids"]:
                self._ensure_entry(ObjectID(b)).refcount += 1
        return {}

    def _h_del_ref(self, conn, msg):
        if not self.is_head:
            self.head.notify("del_ref", oids=msg["oids"])
            return {}
        to_delete = []
        with self._lock:
            for b in msg["oids"]:
                oid = ObjectID(b)
                entry = self.objects.get(oid)
                if entry is None:
                    continue
                entry.refcount -= 1
                if entry.refcount <= 0 and entry.state != PENDING:
                    remote_locs = [
                        nid for nid in entry.locations
                        if nid != self.node_id.binary()
                    ]
                    to_delete.append((oid, entry.in_shm, remote_locs))
                    del self.objects[oid]
        for oid, in_shm, remote_locs in to_delete:
            self._drop_local_copy(oid, in_shm=in_shm)
            for nid in remote_locs:
                client = self._node_client(nid)
                if client is not None:
                    try:
                        client.notify("delete_object", oid=oid.binary())
                    except Exception:  # rt: noqa[RT007] — best-effort fanout to a maybe-dead node; nothing to reply
                        pass
        return {}

    # ------------------------------------------------------------------
    # task pinning helpers (head only — the head owns refcounts)
    # ------------------------------------------------------------------
    def _pin_args(self, spec: dict) -> None:
        """Hold a reference on every ObjectRef argument for the task's
        lifetime so caller-side handle drops can't delete an object a
        queued task still needs (reference: ReferenceCounter pins
        submitted-task arguments, reference_count.h)."""
        with self._lock:
            for kind, payload in spec["args"]:
                if kind == "ref":
                    self._ensure_entry(ObjectID(payload)).refcount += 1

    def _unpin_creation_args(self, runtime: ActorRuntime) -> None:
        """Release an actor's creation-task args exactly once, when the
        actor can no longer restart."""
        with self._lock:
            if runtime.creation_unpinned:
                return
            runtime.creation_unpinned = True
        self._unpin_args(runtime.creation_spec)

    def _unpin_args(self, spec: dict) -> None:
        self._h_del_ref(
            None,
            {
                "oids": [
                    payload
                    for kind, payload in spec["args"]
                    if kind == "ref"
                ]
            },
        )

    # ------------------------------------------------------------------
    # task submission + cluster placement (head)
    # ------------------------------------------------------------------
    def _node_views(self) -> List[NodeView]:
        views = []
        mine = self.node_id.binary()
        for info in self.control.alive_nodes():
            nid = info.node_id.binary()
            if nid == mine:
                avail = self.scheduler.available()
                total = self.scheduler.total()
            else:
                avail = ResourceSet(info.available)
                total = ResourceSet(info.resources)
            views.append(
                NodeView(
                    node_id=nid,
                    total=total,
                    available=avail,
                    labels=info.labels,
                    is_local=(nid == mine),
                )
            )
        return views

    def _submit_cluster(self, spec: dict, schedule: bool = True) -> None:
        """Place a task spec on a node (head only). Infeasible specs
        wait for the cluster to change (reference: tasks queue until
        resources exist). `schedule=False` defers the local dispatch
        pass to the caller — batch ingestion runs ONE pass per batch
        instead of one per spec."""
        task_id = TaskID(spec["task_id"])
        request = ResourceSet(spec.get("resources", {}))
        target = self._policy.pick(
            self._node_views(), request, spec.get("scheduling_strategy")
        )
        if target is None:
            with self._lock:
                self._infeasible[task_id] = spec
            self._record_task_event(spec, "PENDING_NODE_ASSIGNMENT")
            return
        with self._lock:
            entry = self.tasks.get(task_id)
            if entry is not None:
                entry.node = target
            if spec["kind"] == "actor_creation":
                runtime = self.actor_runtimes.get(ActorID(spec["actor_id"]))
                if runtime is not None:
                    runtime.node = target
        if target == self.node_id.binary():
            self._record_task_event(spec, "PENDING_ARGS_AVAIL")
            if spec["kind"] == "actor_creation":
                with self._lock:
                    aid = ActorID(spec["actor_id"])
                    self.actor_hosts.setdefault(aid, ActorHost(spec))
            self.scheduler.enqueue(task_id, request, spec)
            if schedule:
                self._schedule()
            return
        client = self._node_client(target)
        if client is None:
            self._park_infeasible(task_id, spec)
            return
        self._record_task_event(spec, "FORWARDED")
        try:
            client.call("schedule_task", spec=spec)
        except RpcError:
            # Node just died. Clear the assignment so the node-death
            # orphan scan can't also resubmit it (double execution).
            self._park_infeasible(task_id, spec)

    def _park_infeasible(self, task_id: TaskID, spec: dict) -> None:
        with self._lock:
            entry = self.tasks.get(task_id)
            if entry is not None:
                entry.node = None
            self._infeasible[task_id] = spec

    def _retry_infeasible(self) -> None:
        with self._lock:
            pending = [
                (tid, spec)
                for tid, spec in self._infeasible.items()
                if not (
                    tid in self.tasks and self.tasks[tid].state == "DONE"
                )
            ]
            self._infeasible.clear()
        for _, spec in pending:
            self._submit_cluster(spec)

    def _h_submit_task(self, conn, msg):
        spec = msg["spec"]
        if not self.is_head:
            return self.head.call("submit_task", spec=spec)
        task_id = TaskID(spec["task_id"])
        with self._lock:
            self.tasks[task_id] = TaskEntry(
                spec=spec, retries_left=spec.get("max_retries", 0)
            )
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        self._submit_cluster(spec)
        return {}

    def _h_submit_tasks(self, conn, msg):
        """Batched task ingestion: one wire round trip covers a whole
        flat-codec spec batch. Ingestion is IDEMPOTENT by task_id —
        re-sending a batch whose first attempt was lost in transport
        re-ingests only the specs the head never saw, which is what
        makes driver-side batch retry exactly-once. Per-spec decode
        failures ride back as {index: error} so one malformed spec
        fails alone. Dispatch interleaves with ingestion: each batch
        schedules before the connection's ordered drain picks up the
        next frame, so early tasks complete while later batches are
        still arriving."""
        from .wire import SpecCodecError, decode_spec, split_spec_batch

        if not self.is_head:
            return self.head.call(
                "submit_tasks", specs=msg["specs"], count=msg["count"]
            )
        blobs = split_spec_batch(msg["specs"])
        # Decode OUTSIDE the hot lock: a 256-spec frame (with embedded
        # pickles for cold fields) is milliseconds of pure decode, and
        # heartbeats/dispatch must not stall behind it.
        decoded = []
        errors = {}
        for i, blob in enumerate(blobs):
            try:
                spec = decode_spec(blob)
                decoded.append((TaskID(spec["task_id"]), spec))
            except (SpecCodecError, ValueError) as e:
                errors[i] = repr(e)
        accepted = []
        with self._lock:
            for task_id, spec in decoded:
                if task_id in self.tasks:
                    continue  # retried batch: already ingested
                self.tasks[task_id] = TaskEntry(
                    spec=spec, retries_left=spec.get("max_retries", 0)
                )
                for ret in spec["returns"]:
                    self._ensure_entry(ObjectID(ret))
                accepted.append(spec)
        for spec in accepted:
            self._pin_args(spec)
            self._submit_cluster(spec, schedule=False)
        if accepted:
            # One dispatch pass per batch, not per spec: enqueue is
            # O(1), and the pass runs while the NEXT batch is still in
            # the socket — submit-flood ingestion and dispatch
            # interleave at batch granularity.
            self._schedule()
        reply = {"accepted": len(accepted)}
        if errors:
            reply["errors"] = errors
        return reply

    def _h_schedule_task(self, conn, msg):
        """Head forwarded a task to run on this node."""
        spec = msg["spec"]
        task_id = TaskID(spec["task_id"])
        re_report = None
        with self._lock:
            if spec["kind"] == "actor_creation":
                aid = ActorID(spec["actor_id"])
                host = self.actor_hosts.get(aid)
                if host is not None:
                    # Already hosting/creating this actor — a restarted
                    # head re-dispatched a creation this node finished
                    # (or still runs). Re-report instead of duplicating
                    # the instance.
                    if host.worker_conn_id is not None:
                        re_report = aid
                    else:
                        return {}
                else:
                    self.actor_hosts[aid] = ActorHost(spec)
            if re_report is None:
                self.tasks[task_id] = TaskEntry(
                    spec=spec, retries_left=spec.get("max_retries", 0)
                )
        if re_report is not None:
            # On worker nodes this is a synchronous RPC to the head —
            # a slow head must never wedge this node's dispatch lock
            # (every other handler and the heartbeat block on it).
            self._control_actor_created(
                re_report, False, self.node_id.binary()
            )
            return {}
        self.scheduler.enqueue(
            task_id, ResourceSet(spec.get("resources", {})), spec
        )
        self._schedule()
        return {}

    def _h_task_finished(self, conn, msg):
        """A node reports final task completion (head only).
        Idempotent: a task already finalized (e.g. failed via
        _fail_task_returns) is not unpinned twice."""
        task_id = TaskID(msg["task_id"])
        with self._lock:
            entry = self.tasks.get(task_id)
            if entry is None or entry.state == "DONE":
                return {}
            entry.state = "DONE"
        spec = entry.spec
        self.core_counters.bump(
            "tasks_failed" if msg.get("had_error") else "tasks_finished"
        )
        self._record_task_event(
            spec, "FAILED" if msg.get("had_error") else "FINISHED"
        )
        if spec["kind"] == "actor_task":
            with self._lock:
                runtime = self.actor_runtimes.get(ActorID(spec["actor_id"]))
                if runtime is not None:
                    runtime.inflight.pop(task_id, None)
        self._unpin_args(spec)
        return {}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def _h_create_actor(self, conn, msg):
        spec = msg["spec"]
        if not self.is_head:
            return self.head.call("create_actor", spec=spec)
        self.core_counters.bump("actors_created")
        actor_id = ActorID(spec["actor_id"])
        info = ActorInfo(
            actor_id=actor_id,
            name=spec.get("name"),
            namespace=spec.get("namespace", "default"),  # rt: noqa[RT006] — wire-compat: specs from old clients lack the field
            state=ACTOR_PENDING_CREATION,
            class_name=spec.get("class_name", ""),
            max_restarts=spec.get("max_restarts", 0),
        )
        try:
            self.control.register_actor(info)
        except Exception as e:
            # Creates arrive as one-way notifies (pipelined), so a
            # registration error (duplicate name) can't ride an RPC
            # reply — it surfaces the way every other actor failure
            # does: the creation task's return object seals with the
            # error and the first method result raises it.
            self._fail_task_returns(
                spec, "ActorDiedError", f"actor registration failed: {e}"
            )
            return {}
        # Creation spec rides the op log so a restarted head can
        # rebuild this runtime record (and restart the actor if its
        # host later dies).
        self.control.log_extra("actor_spec", spec)
        with self._lock:
            self.actor_runtimes[actor_id] = ActorRuntime(
                creation_spec=spec, info=info
            )
            task_id = TaskID(spec["task_id"])
            self.tasks[task_id] = TaskEntry(spec=spec)
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        self._submit_cluster(spec)
        return {}

    def _h_submit_actor_task(self, conn, msg):
        spec = msg["spec"]
        if not self.is_head:
            return self.head.call("submit_actor_task", spec=spec)
        actor_id = ActorID(spec["actor_id"])
        task_id = TaskID(spec["task_id"])
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            self.tasks[task_id] = TaskEntry(
                spec=spec, retries_left=spec.get("max_retries", 0)
            )
            for ret in spec["returns"]:
                self._ensure_entry(ObjectID(ret))
        self._pin_args(spec)
        if runtime is None or runtime.info.state == ACTOR_DEAD:
            self._fail_task_returns(
                spec, "ActorDiedError", "actor is dead"
            )
            return {}
        self._route_actor_task(runtime, spec)
        return {}

    def _route_actor_task(self, runtime: ActorRuntime, spec: dict) -> None:
        """Deliver an actor task to its hosting node, or queue while the
        actor is pending/restarting (head only)."""
        task_id = TaskID(spec["task_id"])
        with self._lock:
            if runtime.info.state != ACTOR_ALIVE or runtime.node is None:
                runtime.pending.append(spec)
                return
            runtime.inflight[task_id] = spec
            target = runtime.node
        if target == self.node_id.binary():
            self._host_push_task(ActorID(spec["actor_id"]), spec)
            return
        client = self._node_client(target)
        if client is None:
            self._fail_task_returns(
                spec, "ActorUnavailableError", "actor node unreachable"
            )
            return
        try:
            client.call("actor_task", spec=spec)
        except RpcError:
            self._fail_task_returns(
                spec, "ActorUnavailableError", "actor node unreachable"
            )

    def _h_actor_task(self, conn, msg):
        """Head forwards an actor task to this hosting node."""
        spec = msg["spec"]
        self._host_push_task(ActorID(spec["actor_id"]), spec)
        return {}

    def _host_push_task(self, actor_id: ActorID, spec: dict) -> None:
        with self._lock:
            host = self.actor_hosts.get(actor_id)
            if host is None:
                host = self.actor_hosts.setdefault(actor_id, ActorHost(spec))
            worker = (
                self.workers.get(host.worker_conn_id)
                if host.worker_conn_id is not None
                else None
            )
            if worker is not None:
                host.inflight[TaskID(spec["task_id"])] = spec
                worker.conn.push("execute_task", {"spec": spec})
            else:
                host.pending.append(spec)

    def _h_task_done(self, conn, msg):
        task_id = TaskID(msg["task_id"])
        error = msg.get("error")  # serialized error payload or None
        system = msg.get("system_error", False)
        with self._lock:
            winfo = self.workers.get(conn.conn_id)
            entry = self.tasks.get(task_id)
        if entry is None:
            return {}
        spec = entry.spec
        if error is not None and system and entry.retries_left > 0:
            # System failures retry with the same task id → same return
            # object ids, the property lineage reconstruction relies on
            # (reference: TaskManager::RetryTaskIfPossible).
            entry.retries_left -= 1
            self._record_task_event(spec, "RETRY")
            self.scheduler.release(task_id)
            self.scheduler.enqueue(
                task_id, ResourceSet(spec.get("resources", {})), spec
            )
        else:
            if error is not None:
                for ret in spec["returns"]:
                    self._seal_error(ObjectID(ret), error)
            if spec["kind"] == "actor_creation":
                self._on_actor_created_host(spec, error, conn.conn_id)
                if error is not None:
                    self.scheduler.release(task_id)
                elif spec.get("release_creation_resources"):
                    # Default-resource actor: the 1 CPU gated placement
                    # only (reference DEFAULT_ACTOR_CREATION_CPU_SIMPLE
                    # =0) — return it now that the actor is up so more
                    # default actors than node CPUs still come up.
                    # (Idempotent: the later death-path release no-ops.
                    # _h_task_done's fall-through _schedule() dispatches
                    # anything the freed CPU unblocks.)
                    self.scheduler.release(task_id)
                # else: a live actor holds its explicit creation
                # resources until death (_on_actor_worker_death /
                # actor death handling).
            elif spec["kind"] == "actor_task":
                with self._lock:
                    host = self.actor_hosts.get(ActorID(spec["actor_id"]))
                    if host is not None:
                        host.inflight.pop(task_id, None)
            else:
                self.scheduler.release(task_id)
            # Final-completion bookkeeping lives on the head.
            if spec["kind"] != "actor_creation":
                if self.is_head:
                    self._h_task_finished(
                        None,
                        {"task_id": msg["task_id"], "had_error": error is not None},
                    )
                else:
                    self.head.notify(
                        "task_finished",
                        task_id=msg["task_id"],
                        had_error=error is not None,
                    )
            with self._lock:
                entry.state = "DONE"
        # Return the worker to the pool (actor workers stay pinned).
        with self._lock:
            if winfo is not None and winfo.pinned_actor is None:
                winfo.idle = True
                winfo.idle_since = time.time()
                winfo.current_task = None
        self._schedule()
        return {}

    def _publish_error_event(self, source: str, message: str) -> None:
        """Push a cluster error event to subscribed drivers (reference:
        error messages published per job and printed by the driver,
        worker.py listen_error_messages). Rides the same subscriber
        registry as log streaming — one pubsub, several channels.
        Worker-node failures forward through the head like everything
        else (drivers attach there)."""
        payload = {
            "source": source, "message": message, "time": time.time(),
        }
        if self.is_head:
            self._push_to_subscribers("error_event", payload)
        elif self.head is not None:
            try:
                self.head.notify(
                    "publish_event", channel="error_event",
                    payload=payload,
                )
            except Exception:
                pass

    def _fail_task_returns(self, spec: dict, kind: str, detail: str) -> None:
        from .task_spec import make_error_payload

        payload = make_error_payload(kind, detail)
        for ret in spec["returns"]:
            self._seal_error(ObjectID(ret), payload)
        self._record_task_event(spec, "FAILED")
        self._publish_error_event(
            f"task {spec.get('name') or TaskID(spec['task_id']).hex()[:8]}",
            f"{kind}: {detail}",
        )
        if not self.is_head:
            return
        with self._lock:
            entry = self.tasks.get(TaskID(spec["task_id"]))
            if entry is not None:
                if entry.state == "DONE":
                    return  # already finalized; don't unpin twice
                entry.state = "DONE"
        if spec["kind"] == "actor_creation":
            with self._lock:
                runtime = self.actor_runtimes.get(ActorID(spec["actor_id"]))
            if runtime is not None:
                self._unpin_creation_args(runtime)
            else:
                self._unpin_args(spec)
        else:
            self._unpin_args(spec)

    def _h_cancel_task(self, conn, msg):
        if not self.is_head:
            return self.head.call("cancel_task", task_id=msg["task_id"])
        task_id = TaskID(msg["task_id"])
        cancelled = self.scheduler.cancel(task_id)
        if not cancelled:
            with self._lock:
                entry = self.tasks.get(task_id)
                target = entry.node if entry is not None else None
                if task_id in self._infeasible:
                    del self._infeasible[task_id]
                    cancelled = True
            if not cancelled and target and target != self.node_id.binary():
                client = self._node_client(target)
                if client is not None:
                    try:
                        cancelled = client.call(
                            "cancel_local", task_id=msg["task_id"]
                        )["cancelled"]
                    except RpcError:
                        cancelled = False
        if cancelled:
            with self._lock:
                entry = self.tasks.get(task_id)
            if entry is not None:
                self._fail_task_returns(
                    entry.spec, "TaskCancelledError", "task was cancelled"
                )
        return {"cancelled": cancelled}

    def _h_cancel_local(self, conn, msg):
        task_id = TaskID(msg["task_id"])
        return {"cancelled": self.scheduler.cancel(task_id)}

    # -- host-side actor lifecycle --------------------------------------
    def _on_actor_created_host(
        self, spec: dict, error, worker_conn_id: int
    ) -> None:
        actor_id = ActorID(spec["actor_id"])
        with self._lock:
            host = self.actor_hosts.get(actor_id)
            if host is None:
                return
            if error is not None:
                self.actor_hosts.pop(actor_id, None)
                worker = self.workers.get(worker_conn_id)
                if worker is not None:
                    worker.pinned_actor = None
            else:
                host.worker_conn_id = worker_conn_id
                worker = self.workers.get(worker_conn_id)
                if worker is not None:
                    worker.current_task = None
                    worker.pinned_actor = actor_id
                while host.pending:
                    queued = host.pending.popleft()
                    host.inflight[TaskID(queued["task_id"])] = queued
                    worker.conn.push("execute_task", {"spec": queued})
        self._control_actor_created(
            actor_id, error is not None, self.node_id.binary()
        )

    def _control_actor_created(
        self, actor_id: ActorID, failed: bool, node_id: bytes
    ) -> None:
        if not self.is_head:
            try:
                self.head.call(
                    "actor_created", actor_id=actor_id.binary(),
                    failed=failed, node_id=node_id,
                )
            except RpcError:
                pass
            return
        self._h_actor_created(
            None,
            {
                "actor_id": actor_id.binary(),
                "failed": failed,
                "node_id": node_id,
            },
        )

    def _h_actor_created(self, conn, msg):
        """Creation-task outcome reaches the control plane (head)."""
        actor_id = ActorID(msg["actor_id"])
        failed = msg["failed"]
        node_id = msg["node_id"]
        killed_mid_creation = False
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None:
                return {}
            if runtime.info.state == ACTOR_DEAD:
                killed_mid_creation = True
            elif failed:
                runtime.info.state = ACTOR_DEAD
                pending = list(runtime.pending)
                runtime.pending.clear()
            else:
                runtime.info.state = ACTOR_ALIVE
                runtime.node = node_id
                pending = []
        if killed_mid_creation:
            # Killed while the creation task was queued/running: do
            # not resurrect; recycle the hosting worker so actor state
            # can't leak into later tasks. The kill may RPC another
            # node — never under the head's state lock (a slow node
            # would wedge the whole control plane for the timeout).
            if not failed:
                self._kill_host_worker(actor_id, node_id)
            return {}
        if failed:
            self.control.update_actor_state(
                actor_id, ACTOR_DEAD, death_cause="creation task failed"
            )
            for p in pending:
                self._fail_task_returns(
                    p, "ActorDiedError", "actor creation failed"
                )
            self._unpin_creation_args(runtime)
        else:
            self.control.update_actor_state(
                actor_id, ACTOR_ALIVE, node_id=NodeID(node_id)
            )
            while True:
                with self._lock:
                    if not runtime.pending:
                        break
                    spec = runtime.pending.popleft()
                self._route_actor_task(runtime, spec)
        self._wake_actor_addr_waiters(actor_id)
        return {}

    def _kill_host_worker(self, actor_id: ActorID, node_id: bytes) -> None:
        """Kill the worker process hosting an actor (post-kill cleanup
        when creation finished after kill())."""
        if node_id == self.node_id.binary():
            with self._lock:
                host = self.actor_hosts.pop(actor_id, None)
                worker = (
                    self.workers.get(host.worker_conn_id)
                    if host and host.worker_conn_id is not None
                    else None
                )
                if worker is not None:
                    worker.pinned_actor = None
            if worker is not None:
                try:
                    os.kill(worker.pid, 9)
                except ProcessLookupError:
                    pass
            return
        client = self._node_client(node_id)
        if client is not None:
            try:
                client.call("kill_actor_local", actor_id=actor_id.binary())
            except RpcError:
                pass

    def _on_actor_worker_death(self, winfo: WorkerInfo) -> None:
        actor_id = winfo.pinned_actor
        with self._lock:
            host = self.actor_hosts.pop(actor_id, None)
        creating = (
            winfo.current_task is not None
            and host is not None
            and host.worker_conn_id is None
        )
        if host is not None:
            creation_task = TaskID(host.creation_spec["task_id"])
            self.scheduler.release(creation_task)
        if not self.is_head:
            try:
                self.head.call(
                    "actor_worker_died", actor_id=actor_id.binary(),
                    creating=creating,
                )
            except RpcError:
                pass
            return
        self._h_actor_worker_died(
            None, {"actor_id": actor_id.binary(), "creating": creating}
        )

    def _h_actor_worker_died(self, conn, msg):
        """Hosting worker died; decide restart vs. death (head)."""
        actor_id = ActorID(msg["actor_id"])
        creating = msg.get("creating", False)
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None:
                return {}
            can_restart = (
                runtime.info.max_restarts == -1
                or runtime.info.num_restarts < runtime.info.max_restarts
            ) and not self._shutdown
            inflight = list(runtime.inflight.values())
            runtime.inflight.clear()
        for spec in inflight:
            self._fail_task_returns(
                spec,
                "ActorUnavailableError" if can_restart else "ActorDiedError",
                "actor worker died while executing task",
            )
        if creating and not can_restart:
            self._fail_task_returns(
                runtime.creation_spec,
                "ActorDiedError",
                "actor died during creation",
            )
        if can_restart:
            with self._lock:
                runtime.info.num_restarts += 1
                self.core_counters.bump("actor_restarts")
                runtime.info.state = ACTOR_RESTARTING
                runtime.node = None
            self.control.update_actor_state(actor_id, ACTOR_RESTARTING)
            spec = runtime.creation_spec
            task_id = TaskID(spec["task_id"])
            with self._lock:
                self.tasks[task_id] = TaskEntry(spec=spec)
            self._submit_cluster(spec)
            with self._lock:
                entry = self.tasks.get(task_id)
                runtime.node = entry.node if entry else None
        else:
            self._mark_actor_dead(actor_id, "worker died")
        return {}

    def _mark_actor_dead(self, actor_id: ActorID, cause: str) -> None:
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None:
                return
            already_dead = runtime.info.state == ACTOR_DEAD
            runtime.info.state = ACTOR_DEAD
        if not already_dead:
            # Publish exactly once, on the live->dead transition (kill
            # + later worker-death report would double-announce).
            self._publish_error_event(
                f"actor {actor_id.hex()[:8]}", f"dead: {cause}"
            )
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None:
                return
            pending = list(runtime.pending)
            runtime.pending.clear()
            inflight = list(runtime.inflight.values())
            runtime.inflight.clear()
        self.control.update_actor_state(
            actor_id, ACTOR_DEAD, death_cause=cause
        )
        self._unpin_creation_args(runtime)
        for p in pending + inflight:
            self._fail_task_returns(p, "ActorDiedError", cause)
        self._wake_actor_addr_waiters(actor_id)

    def _h_kill_actor(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "kill_actor", actor_id=msg["actor_id"],
                no_restart=msg.get("no_restart", True),
            )
        actor_id = ActorID(msg["actor_id"])
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
            if runtime is None:
                return {"ok": False}
            if msg.get("no_restart", True):
                runtime.info.max_restarts = 0  # suppress restart
            target = runtime.node
            creation_task = TaskID(runtime.creation_spec["task_id"])
            infeasible = creation_task in self._infeasible
            if infeasible:
                del self._infeasible[creation_task]
        if infeasible:
            self._fail_task_returns(
                runtime.creation_spec,
                "ActorDiedError",
                "actor killed before creation",
            )
            self._mark_actor_dead(actor_id, "killed via kill()")
            return {"ok": True}
        if target is None or target == self.node_id.binary():
            self._kill_actor_local(actor_id)
        else:
            client = self._node_client(target)
            if client is not None:
                try:
                    client.call(
                        "kill_actor_local", actor_id=actor_id.binary()
                    )
                except RpcError:
                    self._mark_actor_dead(actor_id, "actor node unreachable")
        return {"ok": True}

    def _h_kill_actor_local(self, conn, msg):
        self._kill_actor_local(ActorID(msg["actor_id"]))
        return {"ok": True}

    def _kill_actor_local(self, actor_id: ActorID) -> None:
        """Kill the local hosting worker, or cancel a still-queued
        creation task (then report death to the control plane)."""
        with self._lock:
            host = self.actor_hosts.get(actor_id)
            winfo = (
                self.workers.get(host.worker_conn_id)
                if host and host.worker_conn_id is not None
                else None
            )
        if winfo is not None:
            try:
                os.kill(winfo.pid, 9)
            except ProcessLookupError:
                pass
            return
        if host is not None:
            creation_task = TaskID(host.creation_spec["task_id"])
            if self.scheduler.cancel(creation_task):
                with self._lock:
                    self.actor_hosts.pop(actor_id, None)
                self._fail_task_returns(
                    host.creation_spec,
                    "ActorDiedError",
                    "actor killed before creation",
                )
                if self.is_head:
                    self._mark_actor_dead(actor_id, "killed via kill()")
                else:
                    try:
                        self.head.call(
                            "actor_worker_died",
                            actor_id=actor_id.binary(),
                            creating=False,
                        )
                    except RpcError:
                        pass
                return
        # Creation running (worker not yet bound): fall back to marking
        # dead at the control plane; the bind-time check recycles it.
        if self.is_head:
            self._mark_actor_dead(actor_id, "killed via kill()")
        else:
            try:
                self.head.call(
                    "actor_worker_died", actor_id=actor_id.binary(),
                    creating=False,
                )
            except RpcError:
                pass

    def _h_get_named_actor(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "get_named_actor", name=msg["name"],
                namespace=msg.get("namespace", "default"),  # rt: noqa[RT006] — wire-compat fallback for old clients
            )
        info = self.control.get_named_actor(
            msg.get("namespace", "default"), msg["name"]  # rt: noqa[RT006] — wire-compat fallback for old clients
        )
        if info is None:
            return {"found": False}
        with self._lock:
            runtime = self.actor_runtimes.get(info.actor_id)
        return {
            "found": True,
            "actor_id": info.actor_id.binary(),
            "state": info.state,
            "handle_meta": runtime.creation_spec.get("handle_meta")
            if runtime
            else None,
        }

    def _h_get_actor_info(self, conn, msg):
        if not self.is_head:
            return self.head.call("get_actor_info", actor_id=msg["actor_id"])
        actor_id = ActorID(msg["actor_id"])
        with self._lock:
            runtime = self.actor_runtimes.get(actor_id)
        if runtime is None:
            return {"found": False}
        return {
            "found": True,
            "state": runtime.info.state,
            "num_restarts": runtime.info.num_restarts,
            "node_id": NodeID(runtime.node).hex() if runtime.node else None,
        }

    # ------------------------------------------------------------------
    # node death (head)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # placement groups (reference: gcs_placement_group_manager.cc on the
    # head + placement_group_resource_manager.h 2PC on each node)
    # ------------------------------------------------------------------
    def _h_create_placement_group(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "create_placement_group",
                pg_id=msg["pg_id"],
                bundles=msg["bundles"],
                strategy=msg["strategy"],
                name=msg.get("name", ""),
            )
        strategy = msg["strategy"]
        if strategy not in STRATEGIES:
            return {"error": f"unknown strategy {strategy!r}"}
        entry = PGEntry(
            pg_id=msg["pg_id"],
            bundles=list(msg["bundles"]),
            strategy=strategy,
            name=msg.get("name", ""),
        )
        with self._lock:
            if entry.name:
                for other in self.pgs.values():
                    if other.name == entry.name and other.state != "REMOVED":
                        return {
                            "error": f"placement group name {entry.name!r}"
                            " already taken"
                        }
            self.pgs[entry.pg_id] = entry
        self._try_place_pg(entry)
        return {"ok": True}

    def _h_placement_group_state(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "placement_group_state", pg_id=msg["pg_id"]
            )
        entry = self.pgs.get(msg["pg_id"])
        if entry is None:
            return {"state": None}
        return {"state": entry.state, "entry": entry.to_table_entry()}

    def _h_placement_group_table(self, conn, msg):
        if not self.is_head:
            return self.head.call("placement_group_table")
        with self._lock:
            table = [e.to_table_entry() for e in self.pgs.values()]
        return {"table": table}

    def _h_remove_placement_group(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "remove_placement_group", pg_id=msg["pg_id"]
            )
        with self._pg_mutex:
            with self._lock:
                entry = self.pgs.get(msg["pg_id"])
                if entry is None or entry.state == "REMOVED":
                    return {"ok": True}
                entry.state = "REMOVED"
                assignment = list(entry.bundle_nodes)
                entry.bundle_nodes = [None] * len(entry.bundles)
            for index, node in enumerate(assignment):
                if node is not None:
                    self._bundle_call(
                        node,
                        "release_bundle",
                        pg_id=entry.pg_id,
                        bundle_index=index,
                    )
        self._purge_pg_tasks(entry.pg_id.hex())
        self._schedule()
        return {"ok": True}

    def _purge_pg_tasks(self, pg_hex: str) -> None:
        """Fail tasks parked on a removed group's resources — their
        formatted resources can never exist again."""
        with self._lock:
            doomed = [
                (tid, spec)
                for tid, spec in self._infeasible.items()
                if any(
                    pg_hex in name
                    for name in (spec.get("resources") or {})
                )
            ]
            for tid, _ in doomed:
                del self._infeasible[tid]
        for _, spec in doomed:
            self._fail_task_returns(
                spec,
                "TaskError",
                f"placement group {pg_hex} was removed",
            )

    def _try_place_pg(self, entry: PGEntry) -> None:
        """Attempt bundle placement + 2PC; leaves the group PENDING /
        RESCHEDULING when infeasible (retried on cluster change). The
        group mutex serializes against concurrent retries and removal."""
        with self._pg_mutex:
            created = self._try_place_pg_locked(entry)
        if created:
            # Group resources now exist: tasks gated on them can place.
            self._retry_infeasible()
            self._schedule()

    def _try_place_pg_locked(self, entry: PGEntry) -> bool:
        with self._lock:
            if entry.state in ("REMOVED", "CREATED"):
                return False
            missing = [
                i for i, n in enumerate(entry.bundle_nodes) if n is None
            ]
            exclude = []
            if entry.strategy == "STRICT_SPREAD":
                exclude = [n for n in entry.bundle_nodes if n is not None]
        if not missing:
            with self._lock:
                entry.state = "CREATED"
            return True
        assignment = place_bundles(
            [entry.bundles[i] for i in missing],
            entry.strategy if entry.strategy != "STRICT_PACK" or len(
                missing
            ) == len(entry.bundles) else "PACK",
            self._node_views(),
            exclude=exclude,
        )
        if assignment is None:
            return False
        prepared = []
        ok = True
        for offset, index in enumerate(missing):
            node = assignment[offset]
            reply = self._bundle_call(
                node,
                "prepare_bundle",
                pg_id=entry.pg_id,
                bundle_index=index,
                resources=entry.bundles[index],
            )
            if not reply.get("ok"):
                ok = False
                break
            prepared.append((index, node))
        if not ok:
            for index, node in prepared:
                self._bundle_call(
                    node,
                    "release_bundle",
                    pg_id=entry.pg_id,
                    bundle_index=index,
                )
            return False
        committed = []
        uncommitted = []
        for index, node in prepared:
            reply = self._bundle_call(
                node,
                "commit_bundle",
                pg_id=entry.pg_id,
                bundle_index=index,
            )
            if reply.get("ok"):
                committed.append((index, node))
            else:
                # A commit that never lands (RPC loss between prepare
                # and commit) must not let the head record the bundle
                # as placed — the node would hold unformatted resources
                # while tasks queue on {R}_group_{i}_{pg} forever.
                # Reference: gcs_placement_group_manager.cc treats
                # commit failure as placement failure and reschedules.
                uncommitted.append((index, node))
        with self._lock:
            # Committed bundles stay placed (their formatted resources
            # exist and tasks may already be queued or running on
            # them); releasing them here would spuriously fail those
            # tasks. Only the prepared-but-uncommitted bundles are
            # rolled back and retried.
            for index, node in committed:
                entry.bundle_nodes[index] = node
        for index, node in uncommitted:
            self._bundle_call(
                node,
                "release_bundle",
                pg_id=entry.pg_id,
                bundle_index=index,
            )
        with self._lock:
            # _pg_mutex (held by our caller) serializes against
            # remove_placement_group, so the state can't have become
            # REMOVED since the check at the top of this method.
            if all(n is not None for n in entry.bundle_nodes):
                entry.state = "CREATED"
            else:
                entry.state = "RESCHEDULING"
        return not uncommitted

    def _retry_pending_pgs(self) -> None:
        with self._lock:
            pending = [
                e
                for e in self.pgs.values()
                if e.state in ("PENDING", "RESCHEDULING")
            ]
        for entry in pending:
            self._try_place_pg(entry)

    def _maybe_retry_pgs(self) -> None:
        """Capacity just freed somewhere: give pending groups another
        shot. Runs from _schedule(), so a non-blocking gate breaks the
        place -> commit -> _schedule recursion (and makes concurrent
        callers coalesce instead of queueing)."""
        with self._lock:
            pending = any(
                e.state in ("PENDING", "RESCHEDULING")
                for e in self.pgs.values()
            )
        if not pending:
            return
        if not self._pg_retry_gate.acquire(blocking=False):
            return
        try:
            self._retry_pending_pgs()
        finally:
            self._pg_retry_gate.release()

    def _bundle_call(self, node_id: bytes, method: str, **kwargs) -> dict:
        """Run a bundle 2PC verb locally or on a remote node."""
        if node_id == self.node_id.binary():
            handler = getattr(self, "_h_" + method)
            return handler(None, kwargs)
        client = self._node_client(node_id)
        if client is None:
            return {"ok": False}
        try:
            return client.call(method, **kwargs)
        except RpcError:
            return {"ok": False}

    def _h_prepare_bundle(self, conn, msg):
        request = ResourceSet(msg["resources"])
        if not self.scheduler.try_reserve(request):
            return {"ok": False}
        with self._lock:
            self._bundles[(msg["pg_id"], msg["bundle_index"])] = {
                "resources": dict(msg["resources"]),
                "committed": False,
            }
        return {"ok": True}

    def _h_commit_bundle(self, conn, msg):
        key = (msg["pg_id"], msg["bundle_index"])
        with self._lock:
            bundle = self._bundles.get(key)
            if bundle is None:
                return {"ok": False}
            bundle["committed"] = True
        formatted = group_resources(
            msg["pg_id"].hex(), msg["bundle_index"], bundle["resources"]
        )
        self.scheduler.add_capacity(ResourceSet(formatted))
        # Local 2PC calls (conn is None) run with _pg_mutex held; the
        # placing caller triggers scheduling after release.
        if conn is not None:
            self._schedule()
        return {"ok": True}

    def _h_release_bundle(self, conn, msg):
        key = (msg["pg_id"], msg["bundle_index"])
        with self._lock:
            bundle = self._bundles.pop(key, None)
        if bundle is None:
            return {"ok": True}
        if bundle["committed"]:
            # Formatted capacity exists only after commit; a rolled-back
            # prepare must not subtract it.
            formatted = group_resources(
                msg["pg_id"].hex(), msg["bundle_index"], bundle["resources"]
            )
            self.scheduler.remove_capacity(ResourceSet(formatted))
        self.scheduler.add_capacity(ResourceSet(bundle["resources"]))
        # Tasks queued on this node against the group's formatted
        # resources can never run again — fail them now instead of
        # letting the caller's get() hang.
        pg_hex = msg["pg_id"].hex()
        doomed = self.scheduler.drain_queued(
            lambda spec: any(
                pg_hex in name for name in (spec.get("resources") or {})
            )
        )
        for spec in doomed:
            self._fail_task_returns(
                spec, "TaskError", f"placement group {pg_hex} was removed"
            )
            if not self.is_head:
                try:
                    self.head.notify(
                        "task_finished",
                        task_id=spec["task_id"],
                        had_error=True,
                    )
                except Exception:  # rt: noqa[RT007] — head may be mid-failover; resync will reconcile
                    pass
        if conn is not None:
            self._schedule()
        return {"ok": True}

    def _pg_on_node_death(self, node_id: bytes) -> None:
        """Bundles on a dead node are lost; re-place them elsewhere
        (reference: GcsPlacementGroupManager::OnNodeDead reschedules
        lost bundles)."""
        affected = []
        with self._lock:
            for entry in self.pgs.values():
                if entry.state == "REMOVED":
                    continue
                lost = False
                for i, n in enumerate(entry.bundle_nodes):
                    if n == node_id:
                        entry.bundle_nodes[i] = None
                        lost = True
                if lost:
                    if entry.strategy == "STRICT_PACK":
                        # Bundles are co-located: all died together.
                        entry.bundle_nodes = [None] * len(entry.bundles)
                    entry.state = "RESCHEDULING"
                    affected.append(entry)
        for entry in affected:
            self._try_place_pg(entry)

    def _on_node_death(self, node_id: bytes) -> None:
        """Handle a worker node's death: drop locations, retry its
        tasks, restart its actors (reference: GcsNodeManager death
        broadcast + lineage reconstruction,
        object_recovery_manager.h:90)."""
        if self._shutdown:
            return
        self.control.mark_node_dead(NodeID(node_id))
        # Its arena died with it: stop attributing its bytes (the
        # ledger's byte·s already banked what it consumed while alive).
        self._memory_ledger.drop_node(NodeID(node_id).hex())
        with self._lock:
            self._node_sync_versions.pop(node_id, None)
        self._pg_on_node_death(node_id)
        with self._lock:
            client = self._node_clients.pop(node_id, None)
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        # 1. Object copies on the dead node are gone.
        lost_waiting = []
        with self._lock:
            for oid, entry in self.objects.items():
                if node_id in entry.locations:
                    entry.locations.discard(node_id)
                    if (
                        not entry.locations
                        and not entry.in_shm
                        and entry.inline is None
                        and entry.state == SEALED
                        and (entry.waiters or entry.meta_waiters)
                    ):
                        lost_waiting.append(oid)
        for oid in lost_waiting:
            self._maybe_reconstruct(oid)
        # 2. Tasks forwarded to the dead node: retry elsewhere or fail.
        with self._lock:
            orphans = [
                (tid, e)
                for tid, e in self.tasks.items()
                if e.node == node_id and e.state != "DONE"
                and e.spec["kind"] == "normal"
            ]
        for tid, entry in orphans:
            if entry.retries_left > 0:
                entry.retries_left -= 1
                self._record_task_event(entry.spec, "RETRY")
                self._submit_cluster(entry.spec)
            else:
                self._fail_task_returns(
                    entry.spec, "WorkerCrashedError", "node died"
                )
        # 3. Actors hosted on the dead node: restart or die.
        with self._lock:
            dead_actors = [
                aid
                for aid, rt in self.actor_runtimes.items()
                if rt.node == node_id
                and rt.info.state in (
                    ACTOR_ALIVE, ACTOR_PENDING_CREATION, ACTOR_RESTARTING
                )
            ]
        for aid in dead_actors:
            self._h_actor_worker_died(
                None, {"actor_id": aid.binary(), "creating": True}
            )

    def _maybe_reconstruct(self, oid: ObjectID) -> None:
        """Lineage reconstruction: resubmit the task that created a
        lost object (reference: ObjectRecoveryManager::ReconstructObject
        — same task id ⇒ same return ids). Args must still be reachable;
        if they were already released the object is lost for good."""
        task_id = oid.task_id()
        with self._lock:
            entry = self.objects.get(oid)
            task = self.tasks.get(task_id)
            if entry is None:
                return
            if entry.reconstructing or entry.in_shm or entry.inline is not None:
                return
            if entry.state == PENDING:
                return  # already resubmitted (or never produced yet)
            args_gone = task is not None and any(
                kind == "ref" and ObjectID(payload) not in self.objects
                for kind, payload in task.spec["args"]
            )
            if task is None or task.spec["kind"] != "normal" or args_gone:
                from .task_spec import make_error_payload

                payload = make_error_payload(
                    "ObjectLostError",
                    f"object {oid.hex()} lost (all copies gone) and its "
                    "lineage is not reconstructable (creating task "
                    "unknown or its arguments already released)",
                )
            else:
                payload = None
                entry.reconstructing = True
                entry.state = PENDING
                entry.in_shm = False
                entry.locations.clear()
                task.state = "PENDING"
        if payload is not None:
            self._seal_error_local(oid, payload)
            return
        self._record_task_event(task.spec, "RECONSTRUCTING")
        self._pin_args(task.spec)
        self._submit_cluster(task.spec)
        with self._lock:
            entry.reconstructing = False

    # ------------------------------------------------------------------
    # scheduling + worker pool
    # ------------------------------------------------------------------
    def _schedule(self) -> None:
        if self._shutdown:
            return
        self.scheduler.maybe_dispatch(self._deps_ready, self._try_dispatch)
        if self.is_head:
            self._maybe_retry_pgs()

    def _deps_ready(self, spec: dict) -> bool:
        missing = []
        with self._lock:
            for kind, payload in spec.get("args", ()):
                if kind == "ref":
                    oid = ObjectID(payload)
                    entry = self.objects.get(oid)
                    if entry is None or entry.state == PENDING:
                        if not self.is_head:
                            missing.append(oid)
                        else:
                            return False
                    elif entry.state == SEALED and not (
                        entry.in_shm or entry.inline is not None
                    ):
                        missing.append(oid)
        if missing:
            for oid in missing:
                self._ensure_local(oid)
            return False
        return True

    @contextmanager
    def _hot_lock(self, name: str):
        """self._lock, with the acquisition wait recorded to the
        flight recorder — used on the hot paths where a long wait IS
        the diagnosis (dispatch stuck behind a slow handler holding
        the daemon lock)."""
        from .flight_recorder import recorder

        rec = recorder()
        if not rec.enabled:
            with self._lock:
                yield
            return
        t0 = time.monotonic()
        with self._lock:
            waited_ms = (time.monotonic() - t0) * 1e3
            # Zero-wait acquisitions are the steady state on the
            # dispatch path — recording them would let thousands of
            # uninformative entries/s evict the RPC/task events the
            # doctor digests. A long wait IS the diagnosis; only
            # those earn a ring slot.
            if waited_ms >= 1.0:
                rec.record("lock.wait", name, waited_ms)
            yield

    def _try_dispatch(self, task_id: TaskID, spec: dict) -> bool:
        needs_tpu = spec.get("resources", {}).get("TPU", 0) > 0
        if spec["kind"] == "lease":
            return self._try_grant_lease(task_id, spec, needs_tpu)
        with self._hot_lock("dispatch"):
            worker = next(
                (
                    w
                    for w in self.workers.values()
                    if w.idle and w.is_tpu == needs_tpu
                ),
                None,
            )
            if worker is None:
                self._spawn_for_dispatch(spec, needs_tpu)
                return False
            worker.idle = False
            worker.current_task = task_id
            if spec["kind"] == "actor_creation":
                worker.pinned_actor = ActorID(spec["actor_id"])
        self._record_task_event(spec, "RUNNING")
        worker.conn.push("execute_task", {"spec": spec})
        return True

    def _spawn_for_dispatch(self, spec: dict, needs_tpu: bool) -> None:
        """No idle worker took `spec`: grow the pool (caller holds
        self._lock)."""
        if spec["kind"] == "actor_creation":
            # Actors get DEDICATED workers exempt from the task-pool
            # cap — admission is controlled by the actor's resource
            # request, and a capped pool would deadlock many-actor
            # apps (reference: worker_pool starts one process per
            # actor; only in-flight startups are bounded,
            # worker_pool.cc maximum_startup_concurrency). Spawn
            # enough to cover the queued same-type creations (this
            # spec is out of the queue while being tried: +1).
            want = 1 + self.scheduler.count_queued(
                lambda s: s.get("kind") == "actor_creation"
                and (s.get("resources", {}).get("TPU", 0) > 0)
                == needs_tpu
            )
            while (
                self._spawning < self._startup_concurrency
                and want > self._spawning
            ):
                self._spawn_worker(needs_tpu)
        elif self._task_pool_size() + self._spawning < self._max_workers:
            self._spawn_worker(needs_tpu)

    def _task_pool_size(self) -> int:
        """Workers countable against the task-pool cap (caller holds
        self._lock). Actor-pinned workers are dedicated for the
        actor's lifetime and never return to the pool — counting them
        would let a few long-lived actors permanently starve plain
        tasks of worker spawns."""
        return sum(
            1 for w in self.workers.values() if w.pinned_actor is None
        )

    def _try_grant_lease(self, lease_id, spec: dict, needs_tpu: bool) -> bool:
        """Dispatch callback for lease pseudo-tasks: hand an idle
        worker (with a direct endpoint) to the requesting driver."""
        with self._lock:
            if spec["_driver"] not in self.drivers:
                # Requesting driver disconnected while this request was
                # queued (its lease sweep already ran): consume the
                # request and free the reservation, or the worker
                # would be marked leased to a ghost forever.
                self.scheduler.release(lease_id)
                return True
            worker = next(
                (
                    w
                    for w in self.workers.values()
                    if w.idle
                    and w.is_tpu == needs_tpu
                    and w.direct_address
                ),
                None,
            )
            if worker is None:
                if (
                    self._task_pool_size() + self._spawning
                    < self._max_workers
                ):
                    self._spawn_worker(needs_tpu)
                return False
            worker.idle = False
            worker.current_task = lease_id
            worker.leased_by = spec["_driver"]
            self.leases[lease_id] = (worker.conn.conn_id, spec["_driver"])
            worker_id = worker.worker_id.binary()
            address = worker.direct_address
        spec["_conn"].reply(
            spec["_mid"],
            {"lease_id": lease_id, "worker_id": worker_id,
             "address": address},
        )
        return True

    def _worker_env(self, needs_tpu: bool) -> dict:
        env = dict(os.environ)
        env["RT_SOCKET"] = self.socket_path
        env["RT_WORKER_TPU"] = "1" if needs_tpu else "0"
        if not needs_tpu:
            # CPU workers must not touch (or pay the init cost of) the
            # TPU runtime: hide the chips the way the reference scopes
            # accelerator visibility per worker (reference:
            # _private/accelerators/tpu.py:155 TPU_VISIBLE_CHIPS).
            env["TPU_VISIBLE_CHIPS"] = ""
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # axon site hook gate
        # Workers must import this package regardless of their cwd.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH", "")) if p
        )
        return env

    def _ensure_fork_server(self):
        """Warm fork-server template for this node (lazy; cpu-scoped
        env — TPU workers override per spawn)."""
        with self._fork_server_lock:
            if (
                self._fork_server is None
                and self.config.worker_fork_server
            ):
                from .worker_forkserver import ForkServerClient

                self._fork_server = ForkServerClient(
                    self._worker_env(needs_tpu=False),
                    os.path.join(self.session_dir, "forkserver.out"),
                )
                self._fork_server.start()
            return self._fork_server

    def _spawn_worker(self, needs_tpu: bool = False) -> None:
        """Request one worker spawn (non-blocking; callers hold
        self._lock). The actual fork/exec happens on the spawner
        thread — its pipe handshake must never stall dispatch."""
        self._spawning += 1
        self.core_counters.bump("workers_started")
        if self._spawn_thread is None:
            self._spawn_thread = threading.Thread(
                target=self._spawn_loop, daemon=True,
                name=f"spawn:{self.node_id.hex()[:8]}",
            )
            self._spawn_thread.start()
        self._spawn_queue.put(needs_tpu)

    def _spawn_loop(self) -> None:
        while not self._shutdown:
            try:
                needs_tpu = self._spawn_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self._spawn_worker_blocking(needs_tpu)
            except Exception:
                # Counted like a pre-registration death so the spawn
                # slot is reclaimed and the queue can't starve.
                with self._lock:
                    self._spawning = max(0, self._spawning - 1)
                    self._spawn_failures += 1
                    self._spawn_crash_total += 1
                self._schedule()

    def _spawn_worker_blocking(self, needs_tpu: bool) -> None:
        log_path = os.path.join(
            self.session_dir, f"worker-{len(self._worker_procs)}.out"
        )
        proc = None
        fork_server = self._ensure_fork_server()
        if fork_server is not None:
            # Per-spawn deltas derived as a diff against the template's
            # base env (one source of truth: _worker_env; None unsets).
            base = self._worker_env(needs_tpu=False)
            want = self._worker_env(needs_tpu)
            overrides = {
                k: v for k, v in want.items() if base.get(k) != v
            }
            overrides.update(
                {k: None for k in base if k not in want}
            )
            proc = fork_server.spawn(log_path, overrides)
        if proc is None:
            # Cold path: fork server disabled or crashed twice.
            with open(log_path, "ab") as log_file:
                # The child holds its own copy of the fd; closing ours
                # immediately avoids leaking one fd per spawn.
                proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "ray_tpu._private.worker_main"],
                    env=self._worker_env(needs_tpu),
                    stdout=log_file,
                    stderr=subprocess.STDOUT,
                )
        self._worker_procs.append(proc)
        self._watch_worker_start(proc)

    def _watch_worker_start(self, proc: subprocess.Popen) -> None:
        """Detect workers that die before registering (bad env, import
        error) so their spawn slot is reclaimed and the failure is
        surfaced instead of hanging the queue (reference: WorkerPool
        PopWorker failure callbacks, worker_pool.cc:1312).

        ONE watcher thread serves all pending spawns: a thread per
        spawn, each scanning the workers dict on its own 0.2s tick,
        was O(spawns x workers) of pure poll overhead at the
        1000-actor scale."""
        # The window must outlast the worker's own daemon-connect
        # budget (RT_WORKER_CONNECT_TIMEOUT, 60s): a worker still in
        # its connect retry loop is pending, not dead, and dropping it
        # from the watchlist early would leak its startup slot.
        window = 30.0 + float(
            os.environ.get("RT_WORKER_CONNECT_TIMEOUT", "60")
        )
        # Mutable entry: the watch loop appends a grace deadline on
        # first seeing the process exited.
        with self._spawn_watch_lock:
            self._spawn_watchlist.append([proc, time.time() + window])
            if self._spawn_watcher is None or not (
                self._spawn_watcher.is_alive()
            ):
                self._spawn_watcher = threading.Thread(
                    target=self._spawn_watch_loop, daemon=True,
                    name="spawn-watch",
                )
                self._spawn_watcher.start()

    def _spawn_watch_loop(self) -> None:
        while True:
            with self._spawn_watch_lock:
                watched = list(self._spawn_watchlist)
            if not watched:
                with self._spawn_watch_lock:
                    if not self._spawn_watchlist:
                        self._spawn_watcher = None
                        return
                time.sleep(0.05)
                continue
            with self._lock:
                # History, not the live dict: a fast worker can
                # register AND exit between ticks (short trial, idle
                # reap) — that is a success, not a startup crash.
                # Membership per watched pid (not a whole-set copy:
                # the set is O(workers ever) on long-lived daemons),
                # and CONSUMED on resolution so a later reuse of the
                # same pid by a new spawn is judged on its own
                # registration, not this one's.
                registered = {
                    e[0].pid
                    for e in watched
                    if e[0].pid in self._registered_pids_ever
                }
                self._registered_pids_ever -= registered
            now = time.time()
            done = []
            for entry in watched:
                proc, deadline = entry[0], entry[1]
                if proc.pid in registered:
                    done.append(entry)
                    continue
                exited = proc.poll() is not None
                if exited and len(entry) == 2:
                    # First sighting of the exit. The registration RPC
                    # may still be sitting unprocessed in the daemon's
                    # socket buffer (the worker can exit while its
                    # register_client is in flight under load), so give
                    # it one grace window before judging.
                    entry.append(now + 2.0)
                    continue
                if exited and now < entry[2]:
                    continue  # grace window still open
                if exited or now > deadline:
                    done.append(entry)
                    if exited:
                        with self._lock:
                            self._spawning = max(0, self._spawning - 1)
                            self._spawn_failures += 1
                            self._spawn_crash_total += 1
                            failures = self._spawn_failures
                        # Consecutive-failure trip wire. Generous by
                        # default: under heavy load a few slow spawns
                        # die racing their connect timeout while the
                        # SYSTEM is healthy, and nuking the queue for
                        # that turns overload into an outage.
                        limit = int(
                            os.environ.get("RT_SPAWN_FAILURE_LIMIT", "10")
                        )
                        if failures >= limit:
                            self._fail_all_queued(
                                "worker processes are crashing at "
                                "startup; see "
                                f"{self.session_dir}/worker-*.out"
                            )
                        self._schedule()
            if done:
                with self._spawn_watch_lock:
                    for item in done:
                        try:
                            self._spawn_watchlist.remove(item)
                        except ValueError:
                            pass
            time.sleep(0.2)

    def _fail_all_queued(self, detail: str) -> None:
        with self._lock:
            queued = [
                (tid, spec)
                for tid, (_, spec) in list(self.scheduler._queue.items())
            ]
        for tid, spec in queued:
            if self.scheduler.cancel(tid):
                if spec.get("kind") == "lease":
                    # Lease pseudo-tasks have no returns; tell the
                    # requesting driver to use the daemon path.
                    spec["_conn"].reply(
                        spec["_mid"], {"unavailable": True}
                    )
                else:
                    self._fail_task_returns(
                        spec, "WorkerCrashedError", detail
                    )

    def _on_task_worker_death(self, winfo: WorkerInfo) -> None:
        task_id = winfo.current_task
        with self._lock:
            entry = self.tasks.get(task_id)
        if entry is None:
            return
        self.scheduler.release(task_id)
        if entry.retries_left > 0 and not self._shutdown:
            entry.retries_left -= 1
            self._record_task_event(entry.spec, "RETRY")
            self.scheduler.enqueue(
                task_id,
                ResourceSet(entry.spec.get("resources", {})),
                entry.spec,
            )
            self._schedule()
        else:
            self._fail_task_returns(
                entry.spec, "WorkerCrashedError", "worker process died"
            )
            if entry.spec["kind"] != "actor_creation" and not self.is_head:
                self.head.notify(
                    "task_finished",
                    task_id=entry.spec["task_id"],
                    had_error=True,
                )

    # ------------------------------------------------------------------
    # introspection / state API
    # ------------------------------------------------------------------
    def _h_cluster_resources(self, conn, msg):
        if not self.is_head:
            return self.head.call("cluster_resources")
        total = ResourceSet()
        for info in self.control.alive_nodes():
            total = total.add(ResourceSet(info.resources))
        return {"resources": total.to_dict()}

    def _h_available_resources(self, conn, msg):
        if not self.is_head:
            return self.head.call("available_resources")
        total = ResourceSet()
        mine = self.node_id.binary()
        for info in self.control.alive_nodes():
            if info.node_id.binary() == mine:
                total = total.add(self.scheduler.available())
            else:
                total = total.add(ResourceSet(info.available))
        return {"resources": total.to_dict()}

    def _h_state_summary(self, conn, msg):
        if not self.is_head:
            return self.head.call("state_summary")
        summary = self.control.summary()
        summary.update(self.store.size_info())
        if self.spill is not None:
            summary.update(self.spill.stats())
        with self._lock:
            summary["workers"] = len(self.workers)
            summary["queued_tasks"] = self.scheduler.queued_count()
            summary["infeasible_tasks"] = len(self._infeasible)
        return {"summary": summary}

    def _h_event_stats(self, conn, msg):
        """Per-handler RPC timing stats for THIS daemon (reference:
        event_stats.cc dump in the debug state). Unlike most read
        APIs this does not forward to the head — the asker names the
        node whose loop it is diagnosing by connecting to it."""
        from .event_stats import stats

        return {"handlers": stats().snapshot()}

    def _relay_to_node(
        self, method: str, node_id, timeout: float, **fwd
    ) -> Optional[dict]:
        """Shared routing step of the operator RPCs that target a
        worker/daemon by node (profile_worker, flight_recorder,
        worker_inspect): a non-head daemon bounces the call through
        the head, the head calls the owning daemon directly. Returns
        None when `node_id` is absent or THIS node — the caller
        serves the request locally."""
        if not node_id or node_id == self.node_id.binary():
            return None
        if not self.is_head:
            return self.head.call(
                method, timeout=timeout, node_id=node_id, **fwd
            )
        client = self._node_client(node_id)
        if client is None:
            raise ValueError(f"no live node {NodeID(node_id).hex()}")
        return client.call(method, timeout=timeout, **fwd)

    @staticmethod
    def _parallel_map(fn, items: list) -> list:
        """Bounded concurrent map for the operator-driven fan-outs
        (inspect probes, diagnose node pulls, stack captures): one
        slow or unreachable target costs ONE probe window for the
        whole sweep instead of serializing every target behind it —
        several wedged targets in a serial loop would blow the
        caller's own RPC timeout exactly when the doctor is needed."""
        if not items:
            return []
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(8, len(items))
        ) as pool:
            return list(pool.map(fn, items))

    def _call_worker_direct(
        self, pid: int, method: str, timeout: float, **kwargs
    ) -> dict:
        """Call a LOCAL worker's direct endpoint by pid (the other
        shared half of the operator-RPC relay)."""
        with self._lock:
            worker = next(
                (
                    w
                    for w in self.workers.values()
                    if w.pid == pid and w.direct_address
                ),
                None,
            )
        if worker is None:
            raise ValueError(
                f"no local worker with pid {pid} (pass node_id to "
                f"reach a worker on another node)"
            )
        client = RpcClient(worker.direct_address)
        try:
            return client.call(method, timeout=timeout, **kwargs)
        finally:
            client.close()

    #: Forwardable profile parameters (shared by the single-worker
    #: relay, the doctor's stack capture, and the gang fan-out —
    #: `start_at` is the gang window's synchronized start).
    _PROFILE_PARAMS = ("kind", "duration_s", "hz", "top", "start_at")

    def _profile_target(
        self, node_id, pid: int, timeout: float, **params
    ) -> dict:
        """ONE start/stop/collect implementation for every profile
        capture: route to the owning daemon (driver -> head -> node)
        when `node_id` is remote, else call the local worker's direct
        `profile` endpoint. The single-worker RPC, the doctor's
        hung-task stack capture, and the gang-profile fan-out all run
        through here — no per-caller capture paths to drift."""
        reply = self._relay_to_node(
            "profile_worker", node_id, timeout, pid=pid, **params
        )
        if reply is not None:
            return reply
        return self._call_worker_direct(
            pid, "profile", timeout, **params
        )

    def _h_profile_worker(self, conn, msg):
        """Attach an on-demand profiler to a live worker (reference:
        dashboard reporter profile_manager.py py-spy/memray attach;
        here the worker profiles itself in-process —
        _private/profiling.py — reached over its direct endpoint).
        Routing: pid alone targets this node; (node_id, pid) routes
        driver -> head -> owning daemon. Blocks one RPC pool thread
        for the profile window (rare, operator-driven)."""
        params = {
            k: msg[k] for k in self._PROFILE_PARAMS if k in msg
        }
        params.setdefault("kind", "stack")
        timeout = float(msg.get("duration_s", 5.0)) + 30.0
        if "start_at" in params:
            timeout += max(0.0, float(params["start_at"]) - time.time())
        return self._profile_target(
            msg.get("node_id"), msg["pid"], timeout, **params
        )

    def _h_profile_gang(self, conn, msg):
        """Coordinated gang profiling (`rt.profile_gang` /
        `ray_tpu profile --job`): fan ONE synchronized start/stop
        window out to every rank of a gang through the profile relay,
        and merge the per-rank capture artifacts with the gang's
        step-telemetry phases into one chrome trace on a shared
        (unix-epoch-us) clock. Head-only: the step ring that knows
        which (node, pid) hosts each rank lives here."""
        if not self.is_head:
            fwd = {
                k: msg[k]
                for k in ("job", "duration_s", "hz")
                if k in msg
            }
            # Forward timeout tracks the requested window (the head
            # legitimately blocks for duration + fan-out slack) — a
            # fixed value would throw away a long capture that ran
            # to completion.
            return self.head.call(
                "profile_gang",
                timeout=float(msg.get("duration_s", 2.0)) + 120.0,
                **fwd,
            )
        duration_s = min(
            float(msg.get("duration_s", 2.0)),
            self.config.profile_gang_max_duration_s,
        )
        hz = float(msg.get("hz", 100.0))
        job = msg.get("job") or None
        with self._lock:
            step_records = list(self._step_records)
        if job is None:
            # Default to the most recently reporting job — the one an
            # operator watching a slow gang means.
            latest: Dict[str, float] = {}
            for rec in step_records:
                j = str(rec.get("job", ""))
                latest[j] = max(
                    latest.get(j, 0.0), float(rec.get("time", 0.0))
                )
            job = max(latest, key=lambda j: latest[j], default=None)
        job_records = [
            r for r in step_records if str(r.get("job", "")) == job
        ]
        # Gang members = the reporting processes of the job's recent
        # step records; rank identity rides every record already.
        members: Dict[tuple, int] = {}
        for rec in job_records:
            node, pid = rec.get("node"), rec.get("pid")
            if node and pid:
                members[(str(node), int(pid))] = int(
                    rec.get("rank", 0)
                )
        if not members:
            raise ValueError(
                f"no step-reporting ranks found for job {job!r} — "
                "gang profiling needs a gang that reports step "
                "telemetry"
            )
        # Synchronized window: every rank sleeps until start_at, then
        # samples for the same duration — slices across ranks line up
        # on the shared clock instead of staggering by fan-out order.
        start_at = time.time() + 0.5
        timeout = duration_s + 30.0 + (start_at - time.time())

        def capture(item):
            (node_hex, pid), rank = item
            try:
                reply = self._profile_target(
                    bytes.fromhex(node_hex),
                    pid,
                    timeout,
                    kind="gang",
                    duration_s=duration_s,
                    hz=hz,
                    start_at=start_at,
                )
                return rank, reply, None
            except Exception as e:  # noqa: BLE001 — per-rank finding
                return rank, None, repr(e)

        trace: list = []
        ranks: list = []
        errors: Dict[int, str] = {}
        # Dedicated pool sized to the gang: _parallel_map's shared
        # 8-thread cap would serialize ranks 9+ past start_at —
        # every rank must hold an in-flight RPC for the WHOLE window
        # or the "synchronized" slices silently stagger.
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(64, len(members))
        ) as pool:
            captures = list(
                pool.map(
                    capture,
                    sorted(
                        members.items(), key=lambda kv: kv[1]
                    ),
                )
            )
        for rank, reply, err in captures:
            if err is not None:
                errors[rank] = err
                continue
            row = {
                "rank": rank,
                "samples": reply.get("samples", 0),
                "threads": reply.get("threads", 0),
            }
            if reply.get("jax_trace_dir"):
                row["jax_trace_dir"] = reply["jax_trace_dir"]
            ranks.append(row)
            for event in reply.get("events", ()):
                # Re-home each rank's slices under one rank-labeled
                # process row so the merged view reads like the gang.
                event = dict(event)
                event["pid"] = f"rank {rank}"
                event.setdefault("args", {})["rank"] = rank
                trace.append(event)
        # Step-telemetry phases of the same job on the same clock —
        # the markers that say WHICH step the hot stacks sat in.
        from .step_telemetry import steps_to_chrome_trace

        window_records = [
            r
            for r in job_records
            if float(r.get("time", 0.0)) >= start_at - 60.0
        ]
        trace.extend(steps_to_chrome_trace(window_records))
        return {
            "job": job,
            "trace": trace,
            "ranks": ranks,
            "errors": errors,
            "window": {
                "start": start_at,
                "duration_s": duration_s,
            },
        }

    def _h_compile_summary(self, conn, msg):
        """The head's folded compile table + current storm verdict
        (`/api/compile`; the cluster half of
        compile_watch.snapshot())."""
        if not self.is_head:
            return self.head.call("compile_summary")
        from .compile_watch import detect_storms

        with self._lock:
            programs = {
                name: {
                    "compiles": row["compiles"],
                    "total_ms": round(row["total_ms"], 3),
                    "distinct_shapes": len(row["digests"]),
                    "digests": {
                        k: dict(v) for k, v in row["digests"].items()
                    },
                }
                for name, row in self._compile_programs.items()
            }
            storms = detect_storms(
                self._compile_programs,
                self.config.compile_storm_threshold,
            )
        return {"compile": {"programs": programs, "storms": storms}}

    def _h_list_task_events(self, conn, msg):
        if not self.is_head:
            return self.head.call(
                "list_task_events", limit=msg.get("limit", 1000)
            )
        return {"events": self.control.list_task_events(msg.get("limit", 1000))}

    def _h_list_nodes(self, conn, msg):
        if not self.is_head:
            return self.head.call("list_nodes")
        return {
            "nodes": [
                {
                    "node_id": n.node_id.hex(),
                    "address": n.address,
                    "resources": n.resources,
                    "available": n.available,
                    "labels": n.labels,
                    "alive": n.alive,
                    "is_head": n.is_head,
                }
                for n in self.control.nodes.values()
            ]
        }

    def _h_list_actors(self, conn, msg):
        if not self.is_head:
            return self.head.call("list_actors")
        with self._lock:
            return {
                "actors": [
                    {
                        "actor_id": rt.info.actor_id.hex(),
                        "name": rt.info.name,
                        "namespace": rt.info.namespace,
                        "state": rt.info.state,
                        "class_name": rt.info.class_name,
                        "num_restarts": rt.info.num_restarts,
                        "node_id": NodeID(rt.node).hex() if rt.node else None,
                    }
                    for rt in self.actor_runtimes.values()
                ]
            }

    def _h_list_objects(self, conn, msg):
        """Node-local object table for the state API (reference:
        node_manager.cc:780 HandleGetObjectsInfo). Largest first
        BEFORE truncating: dict order here is creation order, so a
        plain [:limit] under load dropped an arbitrary slice — the
        big consumers an operator is actually after (same bug class
        as the list_tasks newest-first fix)."""
        limit = int(msg.get("limit", 1000))
        now = time.time()
        # Snapshot under the lock, sort + build rows outside it (the
        # _node_memory_report pattern): the O(N log N) pass over a
        # large table must not stall the seal/get/schedule hot paths.
        with self._lock:
            entries = [
                # locations is a live set: tuple-copy it here so the
                # row build can't race a concurrent seal's add().
                (oid, entry, tuple(entry.locations),
                 oid in self._primary_pins)
                for oid, entry in self.objects.items()
            ]
        entries.sort(key=lambda item: item[1].size, reverse=True)
        out = []
        for oid, entry, locations, pinned in entries[:limit]:
            out.append(
                {
                    "object_id": oid.hex(),
                    "state": entry.state,
                    "size": entry.size,
                    "in_shm": entry.in_shm,
                    "inline": entry.inline is not None,
                    "locations": [
                        NodeID(n).hex() for n in locations
                    ],
                    "ref_count": entry.refcount,
                    # Ledger attribution columns (ISSUE 14).
                    "job": entry.owner_job,
                    "owner": entry.owner,
                    "age_s": (
                        round(now - entry.created_ts, 3)
                        if entry.created_ts
                        else 0.0
                    ),
                    "spilled": entry.spilled,
                    "pinned": pinned,
                    # Data-plane columns (ISSUE 20): where the bytes
                    # live, how many copies exist, and how THIS node's
                    # copy materialised ("" = sealed in place).
                    "node": (
                        min(NodeID(n).hex() for n in locations)
                        if locations
                        else (
                            self.node_id.hex()
                            if entry.in_shm or entry.spilled
                            else ""
                        )
                    ),
                    "copies": (
                        len(locations)
                        if locations
                        else int(
                            entry.in_shm
                            or entry.spilled
                            or entry.inline is not None
                        )
                    ),
                    "source": (
                        "inline"
                        if entry.inline is not None
                        else entry.source or
                        ("local" if entry.state == SEALED else "")
                    ),
                }
            )
        return {"objects": out}

    def _h_cluster_load(self, conn, msg):
        """Pending demand + per-node utilization for the autoscaler
        (reference: GcsAutoscalerStateManager serving cluster resource
        state / pending demand via autoscaler.proto)."""
        if not self.is_head:
            return self.head.call("cluster_load")
        with self._lock:
            infeasible = [
                dict(spec.get("resources") or {})
                for spec in self._infeasible.values()
            ]
            pending_pgs = [
                {"strategy": e.strategy, "bundles": list(e.bundles)}
                for e in self.pgs.values()
                if e.state in ("PENDING", "RESCHEDULING")
            ]
        nodes = []
        mine = self.node_id.binary()
        for info in self.control.alive_nodes():
            nid = info.node_id.binary()
            if nid == mine:
                available = self.scheduler.available().to_dict()
                total = self.scheduler.total().to_dict()
                queued = self.scheduler.queued_count()
            else:
                available = dict(info.available)
                total = dict(info.resources)
                queued = info.queued
            nodes.append(
                {
                    "node_id": info.node_id.hex(),
                    "is_head": info.is_head,
                    "total": total,
                    "available": available,
                    "queued": queued,
                    # Provider-node mapping for the autoscaler: a
                    # multi-host TPU slice is ONE provider node whose
                    # N host daemons each carry the provider-node
                    # label (reference: GCP provider matches instances
                    # to raylets by ip; labels are the tpu-native
                    # equivalent that survives NAT/fake clusters).
                    "labels": dict(info.labels or {}),
                }
            )
        return {
            "infeasible": infeasible,
            "pending_placement_groups": pending_pgs,
            "nodes": nodes,
            "resource_requests": self._resource_requests,
        }

    def _h_request_resources(self, conn, msg):
        """Standing autoscaler target (reference:
        ray.autoscaler.sdk.request_resources /
        GcsAutoscalerStateManager::HandleRequestClusterResource
        Constraint): REPLACE semantics — the latest call's bundles are
        the whole target; an empty list clears it. Persisted only in
        head memory: a restarted head forgets the hint, exactly like
        the reference."""
        if not self.is_head:
            return self.head.call(
                "request_resources", bundles=msg["bundles"]
            )
        self._resource_requests = [  # rt: noqa[RT201] — REPLACE semantics by design: a single atomic list store, latest caller wins
            dict(b) for b in msg["bundles"] if b
        ]
        return {"count": len(self._resource_requests)}

    # ------------------------------------------------------------------
    # OOM defense (reference: MemoryMonitor + worker killing policies)
    # ------------------------------------------------------------------
    def _oom_candidates(self) -> list:
        from .memory_monitor import process_rss

        out = []
        with self._lock:
            workers = list(self.workers.values())
            for winfo in workers:
                if winfo.idle or winfo.current_task is None:
                    continue
                entry = self.tasks.get(winfo.current_task)
                retriable = (
                    entry is not None and entry.retries_left > 0
                )
                out.append(
                    {
                        "pid": winfo.pid,
                        "task_id": winfo.current_task,
                        "retriable": retriable,
                        "rss": process_rss(winfo.pid),
                    }
                )
        return out

    def _oom_kill(self, victim: dict) -> None:
        """SIGKILL the chosen worker; the normal worker-death path
        retries or fails its task."""
        import signal

        self.core_counters.bump("oom_kills")
        try:
            os.kill(victim["pid"], signal.SIGKILL)
        except ProcessLookupError:
            pass

    def _h_metrics_record(self, conn, msg):
        """Batched metric records from local workers; forwarded to the
        head's aggregate table (reference: core-worker metrics flow to
        the node's metrics agent, then get scraped centrally)."""
        if not self.is_head:
            # A failed forward must FAIL the worker's call: replying
            # success here would defeat the sender-side requeue (the
            # _Buffer keeps the batch and retries) and silently lose
            # the records — step telemetry among them. Bounded: an
            # unresponsive head must not pin this daemon's pool
            # threads (one per flushing worker, every 0.5 s) until
            # the node itself stops answering dispatch/heartbeat.
            return self.head.call(
                "metrics_record",
                records=msg["records"],
                sender=msg.get("sender"),
                seq=msg.get("seq"),
                timeout=30.0,
            )
        with self._lock:
            sender, seq = msg.get("sender"), msg.get("seq")
            entry = None
            if sender is not None and seq is not None:
                sender = str(sender)
                seq = int(seq)
                entry = self._metrics_seen.pop(sender, None)
                if entry is None:
                    entry = [0, set()]
                # Re-insert at the END: eviction below pops the
                # LEAST-RECENTLY-USED sender, never one still
                # actively flushing (evicting an active sender would
                # re-enable the redelivery double-count this entry
                # exists to prevent).
                self._metrics_seen[sender] = entry
                if seq <= entry[0] or seq in entry[1]:
                    # Redelivery of a batch whose reply was lost —
                    # already folded in, ack without re-applying.
                    return {}
                while len(self._metrics_seen) > 4096:
                    self._metrics_seen.pop(
                        next(iter(self._metrics_seen))
                    )
            for rec in msg["records"]:
                try:
                    self._apply_metric_record(rec)
                except Exception as e:
                    # A malformed record (e.g. a hand-rolled
                    # report_step extra whose items aren't
                    # 2-tuples) can never succeed on a retry:
                    # skipping it — visibly, via this ring — is the
                    # only option that neither wedges the sender's
                    # requeue loop nor loses the good records
                    # around it.
                    from .flight_recorder import record as _fr

                    _fr(
                        "metrics.drop",
                        type(e).__name__,
                        0.0,
                        {"error": True, "detail": str(e)[:200]},
                    )
            # Seal the seq only now, with the batch folded in:
            # marking it seen before applying would turn a crash
            # mid-batch into silent permanent loss (the sender's
            # retry of the partially-applied batch would be dropped
            # as a duplicate). Then compact: senders deliver sealed
            # batches in seq order, so the contiguous prefix
            # collapses into the high-water mark and steady state
            # keeps nothing resident per sender but two ints.
            if entry is not None:
                wm, seen = entry[0], entry[1]
                seen.add(seq)
                while wm + 1 in seen:
                    wm += 1
                    seen.discard(wm)
                entry[0] = wm
                if len(seen) > 4096:
                    seen.discard(min(seen))
        return {}

    def _apply_metric_record(self, rec) -> None:
        """Fold ONE metrics-pipe record into the head's tables
        (caller holds self._lock)."""
        kind, name, value, tags = rec[:4]
        if kind == "step":
            # Train-step telemetry rides the metrics pipe as
            # its own record kind: `tags` carries the phase
            # payload (train/telemetry.py), `value` the step
            # index. Stored whole — skew needs per-step,
            # per-rank records, not aggregates.
            record = {
                "step": int(value),
                "time": time.time(),
                **{str(k): v for k, v in tags},
            }
            self._step_records.append(record)
            # Chip·s accounting accumulates at APPEND time (exact):
            # the bounded diagnostic ring can evict records between
            # periodic ledger folds under a fast gang's record rate.
            if self.config.memory_report_interval_s > 0:
                self._memory_ledger.add_step(record)
            return
        if kind == "compile":
            # XLA compile events ride the pipe like step records:
            # `name` is the program, `value` the compile duration,
            # `tags` the digest/shape payload. Folded into the
            # per-program digest ring the storm detector reads;
            # count/duration AGGREGATES arrive separately as the
            # rt_jax_* counter/histogram records.
            from .compile_watch import fold_record

            info = {str(k): v for k, v in tags}
            info["time"] = time.time()
            fold_record(
                self._compile_programs, str(name), float(value), info
            )
            return
        if kind == "transfer":
            # One completed (or aborted) cross-store data movement,
            # reported by the RECEIVING daemon: `name` is the kind
            # (pull / pull_spill / restore / aborted), `value` the
            # byte count, tags carry (dst, job, ms, src). Folded into
            # the ledger's (job, src, dst) transfer matrix.
            if self.config.memory_report_interval_s > 0:
                info = {str(k): v for k, v in tags}
                self._memory_ledger.record_transfer(
                    info.get("job", ""),
                    str(info.get("src", "")),
                    str(info.get("dst", "")),
                    str(name),
                    int(value),
                    ms=float(info.get("ms", 0.0) or 0.0),
                )
            return
        if kind == "get":
            # Worker-side rt.get provenance aggregates (one record per
            # (provenance, src, task) per flush tick — NEVER per get):
            # `name` is the provenance class, `value` the get count,
            # tags carry (bytes, job, ms, node, src, task).
            if self.config.memory_report_interval_s > 0:
                info = {str(k): v for k, v in tags}
                self._memory_ledger.record_gets(
                    info.get("job", ""),
                    str(name),
                    str(info.get("src", "")),
                    str(info.get("node", "")),
                    str(info.get("task", "")),
                    int(value),
                    int(float(info.get("bytes", 0) or 0)),
                    ms=float(info.get("ms", 0.0) or 0.0),
                )
            return
        declared = tuple(rec[4]) if len(rec) > 4 else ()
        tags = tuple(tuple(t) for t in tags)
        entry = self._metrics_table.setdefault(
            name,
            {"kind": kind, "by_tags": {}},
        )
        if declared and "boundaries" not in entry:
            entry["boundaries"] = declared
        # First-seen boundaries win for BOTH bucketing and
        # labels: a same-named histogram re-declared with
        # different boundaries still lands in one
        # consistently-labeled set of buckets.
        boundaries = entry.get("boundaries", ())
        for bucket in (
            entry,
            entry["by_tags"].setdefault(
                tags,
                {},
            ),
        ):
            if kind == "counter":
                bucket["total"] = (
                    bucket.get("total", 0.0) + value
                )
            elif kind == "gauge":
                bucket["value"] = value
            else:  # histogram
                self._observe_histogram(
                    bucket, value, boundaries
                )

    @staticmethod
    def _observe_histogram(
        bucket: dict, value: float, boundaries: tuple
    ) -> None:
        """Fold one observation into a histogram aggregate: running
        count/sum/min/max, Prometheus-style cumulative-le bucket
        counts against the metric's declared boundaries, and a bounded
        sample reservoir (last 1024) for p50/p95/p99 at summary time
        (underscore keys are internal; metrics_summary strips them)."""
        bucket["count"] = bucket.get("count", 0) + 1
        bucket["sum"] = bucket.get("sum", 0.0) + value
        bucket["min"] = min(bucket.get("min", value), value)
        bucket["max"] = max(bucket.get("max", value), value)
        samples = bucket.get("_samples")
        if samples is None:
            samples = bucket["_samples"] = deque(maxlen=1024)
        samples.append(value)
        if boundaries:
            counts = bucket.get("_bucket_counts")
            if counts is None or len(counts) != len(boundaries) + 1:
                counts = bucket["_bucket_counts"] = [0] * (
                    len(boundaries) + 1
                )
            counts[bisect.bisect_left(boundaries, value)] += 1

    @staticmethod
    def _finish_histogram(bucket: dict, boundaries: tuple) -> dict:
        """Wire/user view of a histogram aggregate: percentiles from
        the sample reservoir + named bucket counts; internal keys
        dropped."""
        out = {
            k: v for k, v in bucket.items() if not k.startswith("_")
        }
        samples = bucket.get("_samples")
        if samples:
            ordered = sorted(samples)
            n = len(ordered)

            def pct(p: float) -> float:
                return ordered[
                    min(n - 1, max(0, math.ceil(p * n) - 1))
                ]

            out["p50"] = pct(0.50)
            out["p95"] = pct(0.95)
            out["p99"] = pct(0.99)
        counts = bucket.get("_bucket_counts")
        if boundaries and counts:
            named = {}
            running = 0
            for bound, c in zip(boundaries, counts):
                running += c
                named[f"le_{bound:g}"] = running
            named["inf"] = running + counts[-1]
            out["buckets"] = named
        return out

    def _dag_edge_summary(self) -> dict:
        """Per-edge channel counters for the doctor verdict: for each
        dag/edges.py edge seen by the head, total hops + bytes (summed
        over directions) and send/recv wait percentiles from the
        histogram reservoirs. ``suspect`` names the edge whose
        consumer waits longest at p50 (>= 1 ms and >= 2 edges) — in a
        pipeline that points at the producing stage. Only
        driver-paced pipeline streams (dir fwd/grad) are eligible: a
        compiled-DAG exec loop's input get (dir "dag") also spans
        idle time between execute() calls, which would convict
        healthy stages of merely idle DAGs."""
        edges: dict = {}
        paced: set = set()
        with self._lock:
            for name, field in (
                ("dag_channel_hops_total", "hops"),
                ("dag_channel_bytes_total", "bytes"),
            ):
                entry = self._metrics_table.get(name)
                if not entry:
                    continue
                for tags, bucket in entry["by_tags"].items():
                    edge = dict(tags).get("edge")
                    if edge is None:
                        continue
                    row = edges.setdefault(edge, {})
                    row[field] = row.get(field, 0) + int(
                        bucket.get("total", 0)
                    )
            for name, field in (
                ("dag_channel_send_wait_ms", "send_wait_ms"),
                ("dag_channel_recv_wait_ms", "recv_wait_ms"),
            ):
                entry = self._metrics_table.get(name)
                if not entry:
                    continue
                boundaries = entry.get("boundaries", ())
                for tags, bucket in entry["by_tags"].items():
                    tag_map = dict(tags)
                    edge = tag_map.get("edge")
                    if edge is None:
                        continue
                    if tag_map.get("dir") in ("fwd", "grad"):
                        paced.add(edge)
                    hist = self._finish_histogram(bucket, boundaries)
                    edges.setdefault(edge, {})[field] = {
                        k: hist[k]
                        for k in ("count", "sum", "p50", "p99", "max")
                        if k in hist
                    }
        if not edges:
            return {}
        out: dict = {"edges": edges}
        waits = [
            (row.get("recv_wait_ms", {}).get("p50", 0.0), edge)
            for edge, row in edges.items()
            if edge in paced
        ]
        waits.sort(reverse=True)
        if len(waits) >= 2 and waits[0][0] >= 1.0:
            p50, edge = waits[0]
            out["suspect"] = {
                "edge": edge,
                "recv_wait_p50_ms": p50,
                "detail": (
                    f"edge {edge}: consumer median recv wait "
                    f"{p50:.1f} ms — the producing side is the "
                    "slowest stage of this DAG/pipeline"
                ),
            }
        return out

    def _rl_summary(self) -> dict:
        """Decoupled-RL dataflow series for the doctor verdict: fold
        the rl_* metrics (rollout_queue.py / weight_sync.py /
        dataflow.py) into one view and NAME the bottleneck —
        `learner` when the queue pins at capacity or sheds stale
        fragments (runners outpace the learner), `runners` when the
        learner's polls keep finding the queue empty (actors can't
        feed it), `balanced` otherwise. The same attribution the
        step-telemetry goodput shows as queue_wait stall share."""
        series: dict = {}
        with self._lock:
            for name in (
                "rl_queue_depth",
                "rl_queue_capacity",
                "rl_queue_learner_version",
                "rl_weight_version",
                "rl_weight_lag",
                "rl_env_steps",
            ):
                entry = self._metrics_table.get(name)
                if not entry:
                    continue
                values = [
                    bucket.get("value")
                    for bucket in entry["by_tags"].values()
                    if bucket.get("value") is not None
                ]
                if values:
                    series[name] = max(values)
            for name in (
                "rl_queue_puts_total",
                "rl_queue_gets_total",
                "rl_queue_full_total",
                "rl_queue_throttled_total",
                "rl_queue_stale_dropped_total",
                "rl_queue_empty_gets_total",
                "rl_env_steps_total",
                "rl_learner_updates_total",
            ):
                entry = self._metrics_table.get(name)
                if not entry:
                    continue
                series[name] = sum(
                    bucket.get("total", 0)
                    for bucket in entry["by_tags"].values()
                )
            entry = self._metrics_table.get("rl_weight_sync_ms")
            if entry and entry["by_tags"]:
                bucket = next(iter(entry["by_tags"].values()))
                hist = self._finish_histogram(
                    bucket, entry.get("boundaries", ())
                )
                series["rl_weight_sync_ms"] = {
                    k: hist[k]
                    for k in ("count", "p50", "p99", "max")
                    if k in hist
                }
        if not series:
            return {}
        out: dict = {"series": series}
        puts = series.get("rl_queue_puts_total", 0)
        full = series.get("rl_queue_full_total", 0)
        stale = series.get("rl_queue_stale_dropped_total", 0) + (
            series.get("rl_queue_throttled_total", 0)
        )
        empty = series.get("rl_queue_empty_gets_total", 0)
        gets = series.get("rl_queue_gets_total", 0)
        depth = series.get("rl_queue_depth", 0)
        capacity = series.get("rl_queue_capacity", 0)
        offered = puts + full
        if offered and (
            full >= 0.1 * offered
            or stale >= 0.1 * offered
            or (capacity and depth >= 0.75 * capacity)
        ):
            verdict, detail = "learner", (
                "queue backpressure engaged (full "
                f"{full}/{offered} puts, {stale} stale-gated, depth "
                f"{depth:g}/{capacity:g}) — runners outpace the "
                "learner; scale the learner or raise max_weight_lag"
            )
        elif (gets + empty) and empty >= 0.6 * (gets + empty) and (
            not capacity or depth <= 0.25 * capacity
        ):
            verdict, detail = "runners", (
                f"learner polls found the queue empty {empty}x vs "
                f"{gets} fragments served — actors can't feed it; "
                "add env runners or check policy-inference latency"
            )
        else:
            verdict, detail = "balanced", (
                "queue occupancy and gates show no sustained "
                "one-sided pressure"
            )
        out["bottleneck"] = verdict
        out["detail"] = detail
        return out

    def _h_metrics_summary(self, conn, msg):
        if not self.is_head:
            return self.head.call("metrics_summary")
        from .metric_defs import PIPE_METRICS

        with self._lock:
            out = {}
            for name, entry in self._metrics_table.items():
                boundaries = entry.get("boundaries", ())
                if entry.get("kind") == "histogram":
                    fmt = lambda b: self._finish_histogram(  # noqa: E731
                        b, boundaries
                    )
                else:
                    fmt = dict
                clean = {
                    k: v
                    for k, v in fmt(entry).items()
                    if k != "by_tags"
                }
                clean["by_tags"] = {
                    "|".join(f"{k}={v}" for k, v in tags):
                    fmt(bucket)
                    for tags, bucket in entry["by_tags"].items()
                }
                # Declared pipe metrics carry their metric_defs
                # description so /metrics renders a HELP line.
                declared_meta = PIPE_METRICS.get(name)
                if declared_meta is not None:
                    clean.setdefault("unit", declared_meta[1])
                    clean.setdefault(
                        "description", declared_meta[2]
                    )
                out[name] = clean
        # Core runtime metrics (reference: stats/metric_defs.cc):
        # head scrapes itself; worker nodes' latest snapshots rode
        # heartbeats. Aggregate = sum across nodes, per-node detail
        # under by_node.
        from .metric_defs import (
            CORE_METRICS,
            GAUGE_AGGREGATION,
            collect,
        )

        core_by_node = {self.node_id.hex(): collect(self)}
        for info in self.control.all_nodes():
            if info.is_head or not info.alive:
                continue
            if info.core_metrics:
                core_by_node[info.node_id.hex()] = info.core_metrics
        for name, (kind, unit, desc) in CORE_METRICS.items():
            values = {
                nid: m[name]
                for nid, m in core_by_node.items()
                if name in m
            }
            if not values:
                continue
            entry = {
                "kind": kind,
                "unit": unit,
                "description": desc,
                "by_node": values,
            }
            agg = (
                "sum"
                if kind == "counter"
                else GAUGE_AGGREGATION.get(name, "sum")
            )
            if agg == "max":
                total = max(values.values())
            elif agg == "mean":
                # Request-weighted: an idle node's lifetime mean must
                # not dilute a busy node's.
                weights = {
                    nid: m.get("rt_rpc_requests_total", 0.0)
                    for nid, m in core_by_node.items()
                    if nid in values
                }
                weight_sum = sum(weights.values())
                if weight_sum > 0:
                    total = (
                        sum(
                            values[nid] * weights[nid]
                            for nid in values
                        )
                        / weight_sum
                    )
                else:
                    total = sum(values.values()) / len(values)
            else:
                total = sum(values.values())
            entry["total" if kind == "counter" else "value"] = total
            out[name] = entry
        # Memory-ledger series (rt_job_*, rt_object_owner_*, the
        # transfer matrix): shaped like table entries so the
        # Prometheus exposition and the time-series snapshot loop pick
        # them up without new plumbing. MERGED, not replaced: the
        # ledger's per-job spill/restore tag series must join the core
        # per-node rt_object_spills/restores_total entries already in
        # `out`, not clobber them.
        self._refresh_memory_ledger()
        for name, entry in self._memory_ledger.metric_entries().items():
            existing = out.get(name)
            if existing is None:
                out[name] = entry
            else:
                existing.setdefault("by_tags", {}).update(
                    entry.get("by_tags", {})
                )
        return {"metrics": out}

    def _timeseries_loop(self) -> None:
        """Head-only: append a compacted metric-table snapshot to the
        bounded time-series ring every interval. Snapshots are cheap
        (scalars per series, no reservoirs) and the ring is bounded,
        so this loop costs O(series) per tick forever."""
        interval = self.config.metrics_timeseries_interval_s
        while not self._shutdown:
            time.sleep(interval)
            try:
                self._timeseries_snapshot()
            except Exception:
                # A malformed record set must not kill history for
                # the daemon's lifetime; the next tick retries.
                pass

    def _timeseries_snapshot(self) -> None:
        """Build + append one snapshot: the compacted metric table
        plus the synthetic per-job goodput series (so 'when did
        goodput drop' is answerable from history, not just 'what is
        it now')."""
        from .step_telemetry import goodput_from_records
        from .timeseries import compact_summary

        snapshot = compact_summary(
            self._h_metrics_summary(None, {})["metrics"]
        )
        with self._lock:
            step_records = list(self._step_records)
        goodput = goodput_from_records(step_records)
        if goodput:
            by_tags = {
                f"job={job}": {"value": row["goodput"]}
                for job, row in goodput.items()
            }
            # Top-level scalar = the job that REPORTED most recently
            # (not the one whose first record arrived last): with a
            # finished job B and a still-training job A, the scalar
            # must keep tracking A.
            latest_job = ""
            for rec in reversed(step_records):
                job = str(rec.get("job", ""))
                if job in goodput:
                    latest_job = job
                    break
            row = goodput.get(
                latest_job, next(iter(goodput.values()))
            )
            snapshot["rt_goodput_fraction"] = {
                "kind": "gauge",
                "value": row["goodput"],
                "by_tags": by_tags,
            }
        self._timeseries.append(snapshot)

    def _h_metrics_timeseries(self, conn, msg):
        """Query the head's snapshot ring: optional `name` filters to
        one series, `since` (unix seconds) to newer-than, `limit`
        keeps the newest N. Worker nodes forward to the head."""
        if not self.is_head:
            fwd = {
                k: msg[k]
                for k in ("name", "since", "limit")
                if k in msg
            }
            return self.head.call(
                "metrics_timeseries", timeout=30.0, **fwd
            )
        return {
            "snapshots": self._timeseries.query(
                name=msg.get("name"),
                since=float(msg.get("since", 0.0) or 0.0),
                limit=int(msg.get("limit", 0) or 0),
            ),
            "interval_s": self.config.metrics_timeseries_interval_s,
            "max_snapshots": self._timeseries.max_snapshots,
        }

    # ------------------------------------------------------------------
    # memory ledger (reference: `ray memory` over ObjectTableData +
    # util/state/memory_utils.py; the fold is off-path like the
    # time-series snapshots — no per-seal/per-get work)
    # ------------------------------------------------------------------
    def _node_memory_report(self) -> dict:
        """Fold THIS node's object table into a compact memory report
        (memory_ledger.build_node_report). The lock is held only for
        the tuple snapshot; the fold (size sort, pid probes) runs
        outside it."""
        from .memory_ledger import build_node_report

        with self._lock:
            entries = [
                (
                    oid,
                    e.size,
                    e.owner_job,
                    e.owner,
                    e.owner_pid,
                    e.created_ts,
                    oid in self._primary_pins,
                    e.spilled,
                    e.in_shm,
                )
                for oid, e in self.objects.items()
                if e.in_shm or e.spilled
            ]
        counters = self.core_counters
        with self._lock:
            job_spill_ops = dict(self._job_spill_ops)
            job_restore_ops = dict(self._job_restore_ops)
        return build_node_report(
            self.node_id.hex(),
            entries,
            self.store.size_info(),
            self.spill.stats() if self.spill is not None else None,
            spill_ops=counters.spills,
            restore_ops=counters.restores,
            job_spill_ops=job_spill_ops,
            job_restore_ops=job_restore_ops,
            topk=self.config.memory_report_topk,
        )

    def _memory_report_loop(self) -> None:
        """Every node: fold the local object table into a report each
        `memory_report_interval_s`. Worker nodes push theirs to the
        head (batched off-path, like the metrics pipe); the head folds
        its own straight into the ledger."""
        interval = self.config.memory_report_interval_s
        while not self._shutdown:
            time.sleep(interval)
            try:
                if self.is_head:
                    self._refresh_memory_ledger(max_age_s=0.0)
                elif self.head is not None:
                    self.head.call(
                        "memory_report",
                        report=self._node_memory_report(),
                        timeout=30.0,
                    )
            except Exception:
                # A missed tick is a stale report, never a crash; the
                # next tick re-folds.
                pass

    def _refresh_memory_ledger(self, max_age_s: float = 1.0) -> None:
        """Head only: fold the head's own report into the ledger,
        rate-limited by `max_age_s` so on-demand readers
        (metrics_summary, doctor) stay fresh without re-folding per
        poll. Chip·s accumulates separately, at step-record append
        (`_apply_metric_record`). `memory_report_interval_s=0` is a
        REAL kill switch: on-demand folds stand down too — worker
        nodes aren't reporting, so a head-only fold would dress a
        half-blind ledger up as cluster truth."""
        if not self.is_head or self.config.memory_report_interval_s <= 0:
            return
        now = time.time()
        if now - self._memory_folded_at < max_age_s:
            return
        self._memory_folded_at = now  # rt: noqa[RT201] — rate-limit timestamp: a lost update means one extra idempotent fold in the same window
        self._memory_ledger.fold(self._node_memory_report())

    def _h_memory_report(self, conn, msg):
        """A worker node's periodic memory report (head only; ignored
        when the head's ledger is disabled so a mixed-config cluster
        can't half-populate it)."""
        if not self.is_head or self.config.memory_report_interval_s <= 0:
            return {}
        self._memory_ledger.fold(dict(msg["report"]))
        return {}

    def _h_memory_summary(self, conn, msg):
        """The cluster memory view `ray_tpu memory` / `/api/memory`
        serve: totals + attribution, per-job usage, per-owner bytes,
        top objects, per-node reports, and the doctor's
        `verdict.memory` over the same data."""
        if not self.is_head:
            return self.head.call("memory_summary", timeout=30.0)
        self._refresh_memory_ledger()
        summary = self._memory_ledger.summary()
        summary["verdict"] = self._memory_verdict()
        if self.config.memory_report_interval_s <= 0:
            summary["disabled"] = True
        return {"memory": summary}

    def _h_transfer_summary(self, conn, msg):
        """The cluster transfer matrix `ray_tpu memory --transfers` /
        `/api/transfers` serve: per-(job, src, dst) flows with
        bytes/ms/op counts, per-job get provenance + locality, the
        hottest consumer task classes, and per-job spill/restore ops."""
        if not self.is_head:
            return self.head.call("transfer_summary", timeout=30.0)
        self._refresh_memory_ledger()
        summary = self._memory_ledger.transfer_summary()
        if (
            self.config.memory_report_interval_s <= 0
            or self.config.transfer_report_interval_s <= 0
        ):
            summary["disabled"] = True
        return {"transfers": summary}

    def _h_object_locations(self, conn, msg):
        """Head-side object location/size index (util.state
        .object_locations): which nodes hold a copy of each sealed
        object, its size and owner — the doctor's misplaced-task
        conviction and user-level placement tooling read this instead
        of scraping per-node object tables. Optional `oids` filters to
        specific ids; largest first, `limit` caps rows."""
        if not self.is_head:
            fwd = {
                k: msg[k] for k in ("oids", "limit") if k in msg
            }
            return self.head.call(
                "object_locations", timeout=30.0, **fwd
            )
        limit = int(msg.get("limit", 1000))
        wanted = None
        if msg.get("oids"):
            wanted = {ObjectID(b) for b in msg["oids"]}
        with self._lock:
            entries = [
                (oid, e, tuple(e.locations))
                for oid, e in self.objects.items()
                if e.state == SEALED
                and (wanted is None or oid in wanted)
            ]
        entries.sort(key=lambda item: item[1].size, reverse=True)
        out = []
        for oid, entry, locations in entries[:limit]:
            out.append(
                {
                    "object_id": oid.hex(),
                    "size": entry.size,
                    "inline": entry.inline is not None,
                    "nodes": sorted(
                        NodeID(n).hex() for n in locations
                    ),
                    "spilled": entry.spilled,
                    "job": entry.owner_job,
                    "owner": entry.owner,
                }
            )
        return {"locations": out}

    def _memory_verdict(
        self, leak_age_s: Optional[float] = None
    ) -> dict:
        """`verdict.memory` over the ledger (head only): nodes near
        capacity, leak suspects past the leak deadline, spill
        thrash."""
        ended = {
            info.job_id.hex()
            for info in self.control.jobs.values()
            if info.end_time is not None
        }
        return self._memory_ledger.verdict(
            leak_age_s=(
                self.config.doctor_leak_age_s
                if leak_age_s is None
                else float(leak_age_s)
            ),
            job_ended=lambda job: job in ended,
        )

    def _h_task_event(self, conn, msg):
        """Workers report state events for direct-transport tasks
        (the daemon never sees those specs; reference: workers batch
        task events to the GCS task manager the same way). Completion
        counts may ride the same frame (the worker's flush sends ONE
        notify per drain, not two)."""
        if msg.get("finished") or msg.get("failed"):
            self._h_task_counts(conn, msg)
        if not self.config.task_events_enabled:
            return {}
        if not self.is_head:
            try:
                self.head.notify("task_event", events=msg["events"])
            except RpcError:
                pass
            return {}
        for event in msg["events"]:
            self.control.add_task_event(event)
        return {}

    def _h_task_counts(self, conn, msg):
        """Batched direct-transport completion counts from local
        workers (independent of the disableable task-event stream;
        metric_defs rt_tasks_*_total). Counted on THIS daemon —
        by_node attribution shows where the task ran; daemon-
        scheduled tasks count on the head via _h_task_finished."""
        self.core_counters.bump(
            "tasks_finished", int(msg.get("finished", 0))
        )
        self.core_counters.bump(
            "tasks_failed", int(msg.get("failed", 0))
        )
        return {}

    def _h_span_event(self, conn, msg):
        """Finished tracing spans (util/tracing.span) land in their
        own ring — separate from task events so neither stream can
        evict the other."""
        if not self.is_head:
            try:
                self.head.notify("span_event", spans=msg["spans"])
            except RpcError:
                pass
            return {}
        with self._lock:
            self._spans.extend(msg["spans"])
        return {}

    def _h_list_spans(self, conn, msg):
        limit = int(msg.get("limit", 1000))
        with self._lock:
            return {"spans": list(self._spans)[-limit:]}

    # ------------------------------------------------------------------
    # flight recorder / stall doctor
    # ------------------------------------------------------------------
    def _h_flight_recorder(self, conn, msg):
        """Pull a flight-recorder ring. No routing args: THIS
        process's ring. `pid` alone: a local worker's ring (over its
        direct endpoint). (`node_id`, [`pid`]): routed driver -> head
        -> owning daemon, mirroring profile_worker. Rings are only
        ever pulled — steady-state recording cost stays one deque
        append per event."""
        from .flight_recorder import recorder

        fwd = {
            k: msg[k] for k in ("limit", "kinds", "pid") if k in msg
        }
        reply = self._relay_to_node(
            "flight_recorder", msg.get("node_id"), 30.0, **fwd
        )
        if reply is not None:
            return reply
        pid = msg.get("pid")
        if pid and pid != os.getpid():
            return self._call_worker_direct(
                pid,
                "flight_recorder",
                10.0,
                **{
                    k: msg[k] for k in ("limit", "kinds") if k in msg
                },
            )
        rec = recorder()
        return {
            "pid": os.getpid(),
            "node_id": self.node_id.binary(),
            "records": rec.snapshot(
                limit=msg.get("limit", 0), kinds=msg.get("kinds")
            ),
            "summary": rec.summary(),
        }

    def _h_lock_witness(self, conn, msg):
        """Pull lock-witness state. No routing args: THIS daemon's
        snapshot. `pid`: a local worker's (over its direct endpoint).
        `node_id`: routed driver -> head -> owning daemon. With
        `all_workers`, the daemon folds its own snapshot plus every
        local worker's into one `procs` list — the doctor's one-RPC-
        per-node pull. A disabled process answers {"enabled": False}
        (the witness never turns on implicitly)."""
        from ray_tpu.devtools.lock_witness import snapshot

        fwd = {
            k: msg[k] for k in ("pid", "all_workers") if k in msg
        }
        reply = self._relay_to_node(
            "lock_witness", msg.get("node_id"), 30.0, **fwd
        )
        if reply is not None:
            return reply
        pid = msg.get("pid")
        if pid and pid != os.getpid():
            return self._call_worker_direct(pid, "lock_witness", 10.0)
        own = snapshot()
        own["node_id"] = self.node_id.binary()
        if not msg.get("all_workers"):
            return own
        with self._lock:
            targets = [
                (w.pid, w.direct_address)
                for w in self.workers.values()
            ]
        procs = [own]
        for wpid, addr in targets:
            if not addr:
                continue
            try:
                client = RpcClient(addr, connect_timeout=2.0)
                try:
                    row = client.call("lock_witness", timeout=5.0)
                finally:
                    client.close()
                row["node_id"] = self.node_id.binary()
                procs.append(row)
            except RpcError:
                # An unreachable worker is the doctor's inspect
                # finding, not a witness finding.
                continue
        return {"procs": procs}

    def _h_worker_inspect(self, conn, msg):
        """Current in-flight tasks of every local worker (with
        `node_id`: of another node's workers), pulled from each
        worker's `inspect` direct endpoint. The doctor's hung-task
        source: direct-transport tasks report state events only at
        completion, so an in-flight hang is visible ONLY here."""
        reply = self._relay_to_node(
            "worker_inspect", msg.get("node_id"), 30.0
        )
        if reply is not None:
            return reply
        with self._lock:
            targets = [
                (w.pid, w.direct_address)
                for w in self.workers.values()
            ]

        def probe(target) -> dict:
            pid, addr = target
            row: dict = {"pid": pid, "node_id": self.node_id.binary()}
            if addr:
                try:
                    client = RpcClient(addr, connect_timeout=2.0)
                    try:
                        reply = client.call("inspect", timeout=5.0)
                    finally:
                        client.close()
                    row["inflight"] = reply.get("inflight", [])
                    row["queued"] = reply.get("queued", 0)
                except RpcError as e:
                    # Only a worker STILL registered after the failed
                    # probe is a finding — one that deregistered in
                    # between (idle reap, pool churn) hit a normal
                    # lifecycle race, not a hang.
                    with self._lock:
                        still_registered = any(
                            w.pid == pid
                            and w.direct_address == addr
                            for w in self.workers.values()
                        )
                    if still_registered:
                        row["error"] = str(e)
                    else:
                        row["exited"] = True
            return row

        return {"workers": self._parallel_map(probe, targets)}

    def _h_step_summary(self, conn, msg):
        """Gang-step telemetry digest (head): per-worker step-time
        stats and per-step skew (max - min step_ms across workers of
        the same step index) — the number that says WHICH worker the
        gang is waiting on (PAPERS: Podracer gang-step skew)."""
        if not self.is_head:
            return self.head.call(
                "step_summary",
                limit=msg.get("limit", 1000),
                records=msg.get("records", False),
            )
        limit = int(msg.get("limit", 1000))
        with self._lock:
            records = list(self._step_records)[-limit:]
        from .step_telemetry import goodput_from_records

        summary = _summarize_steps(records)
        # Per-JOB goodput over the same window (summary stats are
        # most-recent-job only; goodput keeps every job apart so
        # concurrent tenants each get their own fraction).
        summary["goodput"] = goodput_from_records(records)
        reply = {"summary": summary}
        if msg.get("records"):
            # Raw per-step dicts are opt-in: summary readers (the
            # dashboard's steady-state poll among them) shouldn't pay
            # for up to `limit` records they discard.
            reply["records"] = records
        return reply

    def _h_diagnose(self, conn, msg):
        """Stall doctor: fold head task state, per-worker in-flight
        views, step telemetry, and flight-recorder digests into one
        verdict — stragglers (median step time > cluster p50 x
        threshold), hung tasks (in flight / RUNNING past a deadline,
        with the offender's stack auto-captured through the profile
        relay), and dead nodes. Served by the head; operator-driven,
        so the cluster-wide pulls happen HERE, never in steady
        state."""
        if not self.is_head:
            fwd = {
                k: msg[k]
                for k in (
                    "hung_task_s",
                    "straggler_threshold",
                    "capture_stacks",
                    "limit",
                    "leak_age_s",
                    "locality_miss_threshold",
                )
                if k in msg
            }
            return self.head.call("diagnose", timeout=120.0, **fwd)
        hung_s = float(
            msg.get("hung_task_s", self.config.doctor_hung_task_s)
        )
        threshold = float(
            msg.get(
                "straggler_threshold",
                self.config.doctor_straggler_threshold,
            )
        )
        capture = bool(msg.get("capture_stacks", True))
        now = time.time()
        problems: list = []

        # Dead nodes first: everything else is noise if the gang lost
        # a member.
        for info in self.control.all_nodes():
            if not info.alive:
                problems.append(
                    {
                        "kind": "dead_node",
                        "node_id": info.node_id.hex(),
                        "detail": (
                            f"node {info.node_id.hex()[:12]} stopped "
                            "heartbeating"
                        ),
                    }
                )

        # Stragglers from step telemetry — same default window as
        # step_summary, so the two surfaces agree on the same
        # cluster (the full 10k ring would keep convicting a worker
        # that was slow thousands of steps ago and has recovered).
        limit = int(msg.get("limit", 1000))
        with self._lock:
            step_records = list(self._step_records)[-limit:]
        steps = _summarize_steps(step_records)
        from .step_telemetry import goodput_from_records

        # Per-job goodput classification over the same window the
        # straggler stats use, so both surfaces describe one cluster.
        steps["goodput"] = goodput_from_records(step_records)

        # Compiled-DAG / MPMD-pipeline channel edges: fold the
        # dag_channel_* metrics (dag/edges.py) into per-edge rows so
        # a straggler STAGE is named like a straggler rank — the edge
        # whose consumer sits longest in recv names its PRODUCER as
        # the slow side.
        dag = self._dag_edge_summary()
        # Decoupled-RL dataflow: queue levels/gates + weight versions
        # folded into an actor-vs-learner bottleneck attribution.
        rl = self._rl_summary()
        # XLA layer: recompile storms from the head's per-program
        # digest rings and HBM pressure from the step records' device
        # memory fields — promoted to problems so the exit-code
        # contract covers the compiler too (a storm IS a sick
        # cluster: every flagged iteration burns seconds of compile).
        compile_verdict = self._compile_verdict(
            step_records,
            threshold=msg.get("compile_storm_threshold"),
        )
        for storm in compile_verdict.get("storms", ()):
            problem = {
                "kind": "recompile_storm",
                "program": storm["program"],
                "compiles": storm["compiles"],
                "distinct_shapes": storm["distinct_shapes"],
                "delta": storm["delta"],
                "detail": storm["detail"],
            }
            # Static bridge: resolve the storming program name against
            # the accel-pass inventory so the verdict names the RT302
            # source line, not just the symptom. Best-effort — a
            # missing/odd inventory must never break diagnose.
            try:
                from .compile_watch import static_hint

                hint = static_hint(storm["program"])
            except Exception:  # noqa: BLE001
                hint = None
            if hint:
                problem["static_hint"] = hint
            problems.append(problem)
        for row in compile_verdict.get("hbm_pressure", ()):
            problems.append(
                {
                    "kind": "hbm_pressure",
                    "rank": row["rank"],
                    "fraction": row["fraction"],
                    "detail": row["detail"],
                }
            )
        # Memory ledger: near-capacity nodes, leak suspects past the
        # leak deadline, spill thrash — each promoted to a problem so
        # the exit-code contract covers memory health too.
        leak_age_s = float(
            msg.get("leak_age_s", self.config.doctor_leak_age_s)
        )
        self._refresh_memory_ledger(max_age_s=0.0)
        memory = self._memory_verdict(leak_age_s=leak_age_s)
        for row in memory.get("near_capacity", ()):
            problems.append(
                {
                    "kind": "node_near_capacity",
                    "node_id": row["node"],
                    "fraction": row["fraction"],
                    "detail": row["detail"],
                }
            )
        for row in memory.get("leak_suspects", ()):
            problems.append(
                {
                    "kind": "object_leak",
                    "object_id": row["object_id"],
                    "node_id": row["node"],
                    "job": row["job"],
                    "owner": row["owner"],
                    "size": row["size"],
                    "age_s": row["age_s"],
                    "detail": row["detail"],
                }
            )
        for row in memory.get("spill_thrash", ()):
            problems.append(
                {
                    "kind": "spill_thrash",
                    "node_id": row["node"],
                    "detail": row["detail"],
                }
            )
        # Data plane: the transfer matrix folded from get/transfer
        # records names the hottest cross-node flow, classifies each
        # job's data_wait as pull- vs restore-dominated, and convicts
        # misplaced task classes — a consumer pulling most of its
        # bytes from a node that had capacity to run it is a
        # scheduling bug an operator can fix, so it exits 1.
        locality_threshold = float(
            msg.get(
                "locality_miss_threshold",
                self.config.doctor_locality_miss_threshold,
            )
        )

        def _node_has_capacity(node_hex: str) -> bool:
            for info in self.control.alive_nodes():
                if info.node_id.hex() != node_hex:
                    continue
                if info.available:
                    return info.available.get("CPU", 0.0) >= 1.0
                return info.resources.get("CPU", 0.0) >= 1.0
            return False

        data = self._memory_ledger.data_verdict(
            locality_miss_threshold=locality_threshold,
            node_has_capacity=_node_has_capacity,
        )
        for row in data.get("misplaced_tasks", ()):
            problems.append(
                {
                    "kind": "misplaced_task",
                    "task": row["task"],
                    "job": row["job"],
                    "src_node": row["src"],
                    "remote_bytes": row["remote_bytes"],
                    "remote_fraction": row["remote_fraction"],
                    "detail": row["detail"],
                }
            )
        workers = steps.get("workers", {})
        if len(workers) >= 2:
            medians = sorted(
                w["p50_step_ms"] for w in workers.values()
            )
            # LOWER median: with an even worker count the upper
            # median is the straggler's own time (2 workers: the slow
            # one could never exceed threshold x itself).
            cluster_p50 = medians[(len(medians) - 1) // 2]
            for rank in sorted(workers):
                w = workers[rank]
                if (
                    cluster_p50 > 0
                    and w["steps"] >= 3
                    and w["p50_step_ms"] > threshold * cluster_p50
                ):
                    problems.append(
                        {
                            "kind": "straggler",
                            "rank": rank,
                            "p50_step_ms": w["p50_step_ms"],
                            "cluster_p50_ms": round(cluster_p50, 3),
                            "ratio": round(
                                w["p50_step_ms"] / cluster_p50, 2
                            ),
                            "detail": (
                                f"worker rank {rank} median step "
                                f"{w['p50_step_ms']:.1f} ms vs "
                                f"cluster p50 {cluster_p50:.1f} ms "
                                f"(x{w['p50_step_ms'] / cluster_p50:.1f}"
                                f" > x{threshold:g} threshold)"
                            ),
                        }
                    )

        # Hung tasks, source 1: live in-flight views pulled from every
        # worker on every node.
        inspects: list = []
        ring_digests: dict = {}
        try:
            inspects.extend(
                self._h_worker_inspect(conn, {})["workers"]
            )
        except Exception as e:  # noqa: BLE001 — folded into verdict
            # A head that cannot inspect its own workers is itself a
            # finding — the verdict reports it rather than dying.
            problems.append(
                {
                    "kind": "unreachable_node",
                    "node_id": self.node_id.hex(),
                    "detail": f"head worker inspect failed: {e!r}",
                }
            )
        from .flight_recorder import recorder as _fr

        ring_digests[self.node_id.hex()] = _fr().summary()
        remote = []
        for info in self.control.alive_nodes():
            nid = info.node_id.binary()
            if nid == self.node_id.binary():
                continue
            client = self._node_client(nid)
            if client is not None:
                remote.append((info.node_id.hex(), client))

        witness_procs: list = []
        try:
            own = self._h_lock_witness(conn, {"all_workers": True})
            witness_procs.extend(own.get("procs", [own]))
        except Exception as e:  # diagnose still replies; the gap is folded into the verdict below, not dropped
            problems.append(
                {
                    "kind": "unreachable_node",
                    "node_id": self.node_id.hex(),
                    "detail": f"head lock-witness pull failed: {e!r}",
                }
            )

        def pull_node(target):
            # A node's calls run sequentially on its own (dedicated)
            # client; nodes pull concurrently.
            node_hex, client = target
            try:
                workers = client.call(
                    "worker_inspect", timeout=30.0
                )["workers"]
                summary = client.call(
                    "flight_recorder", timeout=15.0, limit=1
                )["summary"]
                witness = client.call(
                    "lock_witness", timeout=15.0, all_workers=True
                ).get("procs", [])
                return node_hex, workers, summary, witness, None
            except RpcError as e:
                return node_hex, [], None, [], str(e)

        for (
            node_hex,
            workers,
            summary,
            witness,
            err,
        ) in self._parallel_map(pull_node, remote):
            if err is not None:
                problems.append(
                    {
                        "kind": "unreachable_node",
                        "node_id": node_hex,
                        "detail": f"inspect failed: {err}",
                    }
                )
                continue
            inspects.extend(workers)
            ring_digests[node_hex] = summary
            witness_procs.extend(witness)
        # Lock-order witness: any process whose RECORDED acquisition
        # graph contains a cycle has already interleaved lock orders
        # that can deadlock — promoted to a problem (doctor exits 1)
        # with both sides' acquiring stacks.
        locks = self._lock_verdict(witness_procs)
        for row in locks["cycles"]:
            problems.append(
                {
                    "kind": "lock_order_inversion",
                    "node_id": row["node_id"],
                    "pid": row["pid"],
                    "locks": row["locks"],
                    "legs": row["legs"],
                    "detail": row["detail"],
                }
            )
        # A task that reported step telemetry within the deadline is
        # making progress — a long-lived in-flight train loop, not a
        # hang (a gang fit task runs ONE task for the whole job;
        # flagging it would page on every healthy run). Keyed by TASK
        # id where the record carries one, so a concurrent actor's
        # OTHER, genuinely wedged call is still caught; (node, pid)
        # only covers records from outside any task (hand-rolled
        # loops).
        progressing_tasks: set = set()
        progressing_procs: set = set()
        for rec in step_records:
            if float(rec.get("time", 0.0)) < now - hung_s:
                continue
            if rec.get("task"):
                progressing_tasks.add(str(rec["task"]))
            elif rec.get("pid") is not None:
                progressing_procs.add(
                    (str(rec.get("node", "")), int(rec["pid"]))
                )
        to_capture: list = []
        for row in inspects:
            if row.get("error"):
                problems.append(
                    {
                        "kind": "unresponsive_worker",
                        "pid": row["pid"],
                        "node_id": NodeID(row["node_id"]).hex(),
                        "detail": (
                            f"worker pid {row['pid']} did not answer "
                            f"inspect: {row['error']}"
                        ),
                    }
                )
                continue
            proc_progressing = (
                NodeID(row["node_id"]).hex(),
                row["pid"],
            ) in progressing_procs
            for task in row.get("inflight", []):
                if task.get("age_s", 0.0) <= hung_s:
                    continue
                if (
                    proc_progressing
                    or task["task_id"] in progressing_tasks
                ):
                    continue
                problem = {
                    "kind": "hung_task",
                    "task_id": task["task_id"],
                    "name": task.get("name", ""),
                    "age_s": task["age_s"],
                    "pid": row["pid"],
                    "node_id": NodeID(row["node_id"]).hex(),
                    "detail": (
                        f"task {task.get('name') or task['task_id'][:12]}"
                        f" has run {task['age_s']:.1f}s on pid "
                        f"{row['pid']} (> {hung_s:g}s deadline)"
                    ),
                }
                if capture:
                    to_capture.append((problem, row))
                problems.append(problem)
        if to_capture:
            # Auto-capture every offender's stacks through the SAME
            # profile relay the gang profiler uses (_profile_target —
            # one start/stop/collect implementation) — the dump an
            # operator would ask for next, taken while it still shows
            # the hang.
            def capture_stack(target):
                problem, row = target
                try:
                    reply = self._profile_target(
                        row["node_id"], row["pid"], 35.0, kind="stack"
                    )
                    problem["stack"] = reply.get("stacks", "")
                except Exception as e:  # noqa: BLE001 — verdict survives
                    problem["stack_error"] = repr(e)

            self._parallel_map(capture_stack, to_capture)

        # Hung tasks, source 2: the head event stream — catches
        # daemon-scheduled tasks whose RUNNING event landed at
        # dispatch but whose worker stopped reporting. Tasks visible
        # in ANY live worker's in-flight view were already judged by
        # source 1 (deadline + step-progress exemption) — source 2
        # only fires for RUNNING tasks NO reachable worker claims,
        # a premise that only holds when EVERY node was probed and
        # answered: with a failed probe, an unreachable node, or a
        # DEAD node (its workers were never probed at all — a task
        # last seen RUNNING there is lost with it, not hung) the
        # unclaimed task may simply live behind the gap (already
        # reported as its own problem), and task events carry no
        # node/pid to tell.
        view_complete = not any(
            row.get("error") for row in inspects
        ) and not any(
            p["kind"] in ("unreachable_node", "dead_node")
            for p in problems
        )
        seen = {
            p["task_id"]
            for p in problems
            if p["kind"] == "hung_task"
        }
        seen.update(
            task["task_id"]
            for row in inspects
            if not row.get("error")
            for task in row.get("inflight", [])
        )
        latest: dict = {}
        for event in self.control.list_task_events(10000):
            latest[event["task_id"]] = event
        for tid, event in latest.items():
            if (
                not view_complete
                or event["state"] != "RUNNING"
                or tid in seen
                or now - event["time"] <= hung_s
            ):
                continue
            problems.append(
                {
                    "kind": "hung_task",
                    "task_id": tid,
                    "name": event.get("name", ""),
                    "age_s": round(now - event["time"], 1),
                    "detail": (
                        f"task {event.get('name') or tid[:12]} has "
                        f"been RUNNING {now - event['time']:.1f}s "
                        "with no further state transition"
                    ),
                }
            )

        summary = self.control.summary()
        return {
            "verdict": {
                "healthy": not problems,
                "problems": problems,
                "steps": steps,
                "dag": dag,
                "rl": rl,
                "compile": compile_verdict,
                "memory": memory,
                "data": data,
                "locks": locks,
                "rpc": ring_digests,
                "nodes": {
                    "total": summary["nodes"],
                    "alive": summary["alive_nodes"],
                },
                "params": {
                    "hung_task_s": hung_s,
                    "straggler_threshold": threshold,
                    "leak_age_s": leak_age_s,
                    "locality_miss_threshold": locality_threshold,
                },
            }
        }

    def _lock_verdict(self, procs: list) -> dict:
        """`verdict.locks`: cluster-wide fold of per-process
        lock-witness snapshots — observed order-graph cycles (each leg
        carries the stack that first created that edge) and
        held-while-blocking ledgers. Empty/disabled processes fold to
        a quiet verdict; `enabled` says whether ANY process ran the
        witness, so a clean verdict with the witness off is not
        mistaken for a clean run."""
        enabled_procs = [p for p in procs if p.get("enabled")]
        cycles: list = []
        blocking: list = []
        dropped = 0
        for proc in enabled_procs:
            node_hex = NodeID(proc["node_id"]).hex()
            pid = proc.get("pid")
            dropped += int(proc.get("dropped_edges", 0))
            for legs in proc.get("cycles", ()):
                names = [leg["from"] for leg in legs]
                cycles.append(
                    {
                        "node_id": node_hex,
                        "pid": pid,
                        "locks": names,
                        "legs": legs,
                        "detail": (
                            f"pid {pid} on node {node_hex[:12]} "
                            "acquired locks in a cyclic order: "
                            + " -> ".join(names + names[:1])
                        ),
                    }
                )
            for row in proc.get("held_blocking", ()):
                blocking.append(
                    dict(row, node_id=node_hex, pid=pid)
                )
        return {
            "enabled": bool(enabled_procs),
            "procs": len(enabled_procs),
            "cycles": cycles,
            "held_blocking": blocking,
            "dropped_edges": dropped,
        }

    def _compile_verdict(
        self, step_records: list, threshold=None
    ) -> dict:
        """`verdict.compile`: per-program compile counts, recompile
        storms (same program, >= threshold distinct shape digests —
        the drifting-shape retrace loop), and HBM pressure (latest
        per-(job, rank) device-memory report >= 90% of capacity).
        Caller must NOT hold self._lock."""
        from .compile_watch import detect_storms

        threshold = int(
            threshold
            if threshold is not None
            else self.config.compile_storm_threshold
        )
        with self._lock:
            programs = {
                name: {
                    "compiles": row["compiles"],
                    "total_ms": round(row["total_ms"], 3),
                    "distinct_shapes": len(row["digests"]),
                }
                for name, row in self._compile_programs.items()
            }
            storms = detect_storms(self._compile_programs, threshold)
        out: dict = {
            "programs": programs,
            "storms": storms,
            "storm_threshold": threshold,
            "hbm_pressure": [],
        }
        # HBM pressure: newest RECENT record per (job, rank) that
        # carries both in-use and limit; absent fields (CPU)
        # contribute nothing — never synthesized. The recency cutoff
        # keeps a finished job's final 92%-HBM records (which sit in
        # the bounded ring until new traffic evicts them) from
        # flipping an idle cluster's doctor to exit 1 forever.
        cutoff = time.time() - 120.0
        latest: Dict[tuple, dict] = {}
        for rec in step_records:
            if "hbm_bytes_in_use" not in rec:
                continue
            if float(rec.get("time", 0.0)) < cutoff:
                continue
            key = (str(rec.get("job", "")), int(rec.get("rank", 0)))
            if float(rec.get("time", 0.0)) >= float(
                latest.get(key, {}).get("time", -1.0)
            ):
                latest[key] = rec
        for (job, rank), rec in sorted(latest.items()):
            limit = int(rec.get("hbm_bytes_limit", 0) or 0)
            in_use = int(rec.get("hbm_bytes_in_use", 0) or 0)
            if limit <= 0:
                continue
            fraction = in_use / limit
            if fraction >= 0.9:
                out["hbm_pressure"].append(
                    {
                        "rank": rank,
                        "job": job,
                        "bytes_in_use": in_use,
                        "bytes_limit": limit,
                        "fraction": round(fraction, 4),
                        "detail": (
                            f"rank {rank} HBM at "
                            f"{100.0 * fraction:.1f}% of capacity "
                            f"({in_use / 2**30:.2f} / "
                            f"{limit / 2**30:.2f} GiB) — next "
                            "allocation or fragmentation spike OOMs "
                            "this rank"
                        ),
                    }
                )
        return out

    def _record_task_event(self, spec: dict, state: str) -> None:
        if state == "RETRY":
            self.core_counters.bump("tasks_retried")
        if not self.config.task_events_enabled:
            return
        if not self.is_head:
            return  # head records events from task_finished reports
        self.control.add_task_event(
            {
                "task_id": spec["task_id"].hex()
                if isinstance(spec["task_id"], bytes)
                else str(spec["task_id"]),
                "name": spec.get("name", ""),
                "kind": spec.get("kind", "normal"),
                "state": state,
                "time": time.time(),
            }
        )

    # ------------------------------------------------------------------
    def kill_worker_tree(self) -> None:
        """SIGKILL every worker process this daemon spawned, plus its
        fork-server, with only a brief bounded reap. Safe to call from
        any state — including a partially-wedged runtime: a 7000-worker
        teardown must not depend on the driver's shutdown path
        completing (a saturated 1-core box once wedged there with the
        whole worker tree pinning the pid table). Kills go through the
        proc HANDLES (Popen no-ops on already-reaped children;
        ForkedProc compares /proc start times), never raw recorded
        pids — a recycled pid must not take down a stranger."""
        self._shutdown = True
        procs = list(self._worker_procs)
        for proc in procs:
            try:
                proc.kill()
            except Exception:
                pass
        # Best-effort non-blocking reap so the killed children release
        # their pid-table slots even if the graceful shutdown path
        # never runs (ForkedProc children are the fork-server's to
        # reap — closing it below reparents them to init).
        deadline = time.monotonic() + 1.0
        for proc in procs:
            if time.monotonic() > deadline:
                break
            try:
                proc.poll()
            except Exception:
                pass
        if self._fork_server is not None:
            try:
                self._fork_server.close()
            except Exception:
                pass

    def shutdown(self) -> None:
        self._shutdown = True
        # Stop the heartbeat/reaper thread before closing the store:
        # its reap_dead_pins must not race the arena unmap.
        hb = getattr(self, "_hb_thread", None)
        if hb is not None and hb.is_alive():
            hb.join(timeout=self.config.heartbeat_interval_s + 1.0)
        if self._memory_monitor is not None:
            self._memory_monitor.stop()
        for proc in self._worker_procs:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        # Bounded TOTAL wait, not per-proc: a per-proc 2s timeout sums
        # to hours across a 7k-worker pool on a loaded box (each stale
        # handle that looks alive burns its full slice); the kill
        # above already guarantees death.
        wait_deadline = time.monotonic() + 10.0
        for proc in self._worker_procs:
            remaining = wait_deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                proc.wait(timeout=min(2.0, remaining))
            except subprocess.TimeoutExpired:
                pass
        # Whatever the deadline cut off still gets a non-blocking reap:
        # SIGKILLed-but-unwaited Popen children of this (long-lived,
        # in-process) daemon host would otherwise sit as zombies
        # pinning pid-table slots.
        for proc in self._worker_procs:
            try:
                proc.poll()
            except Exception:
                pass
        if self._fork_server is not None:
            self._fork_server.close()
        if self.head is not None:
            try:
                self.head.close()
            except Exception:
                pass
        for client in list(self._node_clients.values()) + list(
            self._peer_clients.values()
        ):
            try:
                client.close()
            except Exception:
                pass
        # Detach (never unlink) peers' arenas: the files belong to
        # their daemons.
        for arena in self._peer_arenas.values():
            try:
                arena.close(unlink=False)
            except Exception:
                pass
        self._peer_arenas.clear()
        self.server.close()
        # Reclaim every live shared-memory object of the session.
        with self._lock:
            shm_oids = [
                oid for oid, e in self.objects.items() if e.in_shm
            ]
        with self._lock:
            pinned = list(self._primary_pins)
        for oid in set(pinned) | set(shm_oids):
            self._drop_local_copy(oid)
        self.store.shutdown()
        if self.spill is not None:
            self.spill.shutdown()


class _CallbackConn:
    """Adapter so wait-waiters can sit in ObjectEntry.waiters."""

    def __init__(self, callback):
        self._callback = callback

    def reply(self, mid, payload):
        self._callback()


def _default_store_bytes() -> int:
    try:
        import psutil  # noqa: PLC0415

        total = psutil.virtual_memory().total
    except Exception:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    return int(total * 0.3)
