"""Control-plane state tables (GCS equivalent).

The reference's Global Control Service is a standalone server hosting
node/actor/job/placement-group/worker/task managers over typed tables
with pluggable storage (reference: src/ray/gcs/gcs_server/gcs_server.h,
init order gcs_server.cc:183-233; storage src/ray/gcs/store_client/).

Here the same tables live in one `ControlState` object. On a head node
it is embedded in the node daemon and served over its RPC socket; other
node daemons talk to it remotely — mirroring how every raylet holds a
GcsClient. Persistence (the reference's Redis StoreClient) is a JSON
snapshot hook, sufficient for restart-with-state-recovery semantics.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .ids import ActorID, JobID, NodeID, PlacementGroupID

_FRAME = struct.Struct(">I")


class StateLog:
    """Append-only op log backing control-plane fault tolerance
    (reference role: the Redis store client behind GCS tables,
    src/ray/gcs/store_client/redis_store_client.h — here a length-
    prefixed pickle frame log in the session dir; a head restarted
    over the same session replays it and resumes).

    Frames are `[u32 length][pickle bytes]`. A torn final frame (crash
    mid-write) is detected by length mismatch and dropped."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "ab")  # noqa: SIM115 — lifetime = daemon

    def append(self, op: tuple) -> None:
        payload = pickle.dumps(op, protocol=5)
        with self._lock:
            self._f.write(_FRAME.pack(len(payload)) + payload)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    @staticmethod
    def replay(path: str) -> List[tuple]:
        ops: List[tuple] = []
        if not os.path.exists(path):
            return ops
        with open(path, "rb") as f:
            data = f.read()
        cursor = 0
        while cursor + _FRAME.size <= len(data):
            (length,) = _FRAME.unpack_from(data, cursor)
            cursor += _FRAME.size
            if cursor + length > len(data):
                break  # torn tail frame from a crash mid-write
            try:
                ops.append(pickle.loads(data[cursor:cursor + length]))
            except Exception:
                break
            cursor += length
        return ops

# Actor lifecycle states (reference: src/ray/design_docs/actor_states.rst).
ACTOR_DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
ACTOR_PENDING_CREATION = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.time)
    is_head: bool = False
    # Load view refreshed by heartbeats (reference: ray_syncer resource
    # gossip feeding ClusterResourceManager).
    available: Dict[str, float] = field(default_factory=dict)
    queued: int = 0
    #: Latest core-metrics snapshot (metric_defs.collect) that rode a
    #: heartbeat; the head merges these into metrics_summary.
    core_metrics: Dict[str, float] = field(default_factory=dict)


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: str
    class_name: str
    node_id: Optional[NodeID] = None
    worker_id: Optional[Any] = None
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: Optional[str] = None


@dataclass
class JobInfo:
    job_id: JobID
    driver_pid: int
    start_time: float
    end_time: Optional[float] = None
    entrypoint: str = ""


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    name: Optional[str]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundles: List[Dict[str, float]]
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)


class ControlState:
    """All control-plane tables behind one lock.

    Sub-tables mirror the reference's managers: kv (GcsKvManager),
    nodes (GcsNodeManager), actors (GcsActorManager), jobs
    (GcsJobManager), placement groups (GcsPlacementGroupManager), task
    events (GcsTaskManager ring buffer).
    """

    def __init__(
        self,
        task_events_max: int = 10000,
        log: Optional[StateLog] = None,
    ):
        self._lock = threading.RLock()
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> val
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name)
        self.jobs: Dict[JobID, JobInfo] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.task_events: deque = deque(maxlen=task_events_max)
        self._job_counter = 0
        #: Durable op log; None = in-memory only. Set AFTER replay so
        #: restored ops are not re-logged.
        self.log: Optional[StateLog] = log

    def _log(self, *op) -> None:
        if self.log is not None:
            try:
                self.log.append(op)
            except OSError:
                pass

    def log_extra(self, *op) -> None:
        """Durably record an op owned by the embedding daemon (e.g.
        actor creation specs); handed back verbatim from restore()."""
        self._log(*op)

    def restore(self, ops: List[tuple]) -> List[tuple]:
        """Replay logged ops into the tables (call before attaching a
        live log). Returns ops this class doesn't own (e.g. the
        daemon's actor creation specs) for the caller to apply."""
        extra: List[tuple] = []
        for op in ops:
            kind = op[0]
            if kind == "kv_put":
                self.kv.setdefault(op[1], {})[op[2]] = op[3]
            elif kind == "kv_del":
                self.kv.get(op[1], {}).pop(op[2], None)
            elif kind == "register_node":
                info = op[1]
                # Not alive until it re-registers/heartbeats with the
                # restarted head.
                info.alive = False
                self.nodes[info.node_id] = info
            elif kind == "mark_node_dead":
                if op[1] in self.nodes:
                    self.nodes[op[1]].alive = False
            elif kind == "job_counter":
                self._job_counter = max(self._job_counter, op[1])
            elif kind == "add_job":
                self.jobs[op[1].job_id] = op[1]
            elif kind == "finish_job":
                if op[1] in self.jobs:
                    self.jobs[op[1]].end_time = (
                        op[2] if len(op) > 2 else time.time()
                    )
            elif kind == "register_actor":
                info = op[1]
                self.actors[info.actor_id] = info
                if info.name and info.state != ACTOR_DEAD:
                    self.named_actors[(info.namespace, info.name)] = (
                        info.actor_id
                    )
            elif kind == "update_actor_state":
                info = self.actors.get(op[1])
                if info is not None:
                    info.state = op[2]
                    for k, v in op[3].items():
                        setattr(info, k, v)
                    if op[2] == ACTOR_DEAD and info.name:
                        self.named_actors.pop(
                            (info.namespace, info.name), None
                        )
            elif kind == "add_placement_group":
                self.placement_groups[op[1].pg_id] = op[1]
            else:
                extra.append(op)
        return extra

    # ---- kv (function blobs, cluster config) ----
    def kv_put(self, ns: str, key: str, value: bytes, overwrite=True) -> bool:
        with self._lock:
            table = self.kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            self._log("kv_put", ns, key, value)
            return True

    def kv_get(self, ns: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(ns, {}).get(key)

    def kv_del(self, ns: str, key: str) -> None:
        with self._lock:
            self.kv.get(ns, {}).pop(key, None)
            self._log("kv_del", ns, key)

    def kv_keys(self, ns: str, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    # ---- nodes ----
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self.nodes[info.node_id] = info
            self._log("register_node", info)

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id].last_heartbeat = time.time()

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id].alive = False
                self._log("mark_node_dead", node_id)

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    def all_nodes(self) -> List[NodeInfo]:
        """Snapshot of every known node, dead ones included — readers
        must not iterate `self.nodes` bare (registration on another
        thread would resize the dict mid-iteration)."""
        with self._lock:
            return list(self.nodes.values())

    # ---- jobs ----
    def next_job_id(self) -> JobID:
        with self._lock:
            self._job_counter += 1
            self._log("job_counter", self._job_counter)
            return JobID.from_int(self._job_counter)

    def add_job(self, info: JobInfo) -> None:
        with self._lock:
            self.jobs[info.job_id] = info
            self._log("add_job", info)

    def finish_job(self, job_id: JobID) -> None:
        with self._lock:
            if job_id in self.jobs:
                now = time.time()
                self.jobs[job_id].end_time = now
                self._log("finish_job", job_id, now)

    # ---- actors ----
    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(
                        f"Actor name {info.name!r} already taken in "
                        f"namespace {info.namespace!r}"
                    )
                self.named_actors[key] = info.actor_id
            self.actors[info.actor_id] = info
            self._log("register_actor", info)

    def update_actor_state(self, actor_id: ActorID, state: str, **kw) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None:
                return
            info.state = state
            for k, v in kw.items():
                setattr(info, k, v)
            if state == ACTOR_DEAD and info.name:
                self.named_actors.pop((info.namespace, info.name), None)
            self._log("update_actor_state", actor_id, state, kw)

    def get_named_actor(self, namespace: str, name: str) -> Optional[ActorInfo]:
        with self._lock:
            actor_id = self.named_actors.get((namespace, name))
            return self.actors.get(actor_id) if actor_id else None

    # ---- placement groups ----
    def add_placement_group(self, info: PlacementGroupInfo) -> None:
        with self._lock:
            self.placement_groups[info.pg_id] = info
            self._log("add_placement_group", info)

    # ---- task events (observability ring buffer) ----
    def add_task_event(self, event: dict) -> None:
        with self._lock:
            self.task_events.append(event)

    def list_task_events(self, limit: int = 1000) -> List[dict]:
        with self._lock:
            return list(self.task_events)[-limit:]

    # ---- state API snapshot ----
    def summary(self) -> dict:
        with self._lock:
            return {
                "nodes": len(self.nodes),
                "alive_nodes": sum(1 for n in self.nodes.values() if n.alive),
                "actors": len(self.actors),
                "jobs": len(self.jobs),
                "placement_groups": len(self.placement_groups),
            }
