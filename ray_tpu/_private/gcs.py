"""Control-plane state tables (GCS equivalent).

The reference's Global Control Service is a standalone server hosting
node/actor/job/placement-group/worker/task managers over typed tables
with pluggable storage (reference: src/ray/gcs/gcs_server/gcs_server.h,
init order gcs_server.cc:183-233; storage src/ray/gcs/store_client/).

Here the same tables live in one `ControlState` object. On a head node
it is embedded in the node daemon and served over its RPC socket; other
node daemons talk to it remotely — mirroring how every raylet holds a
GcsClient. Persistence (the reference's Redis StoreClient) is a JSON
snapshot hook, sufficient for restart-with-state-recovery semantics.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .ids import ActorID, JobID, NodeID, PlacementGroupID

# Actor lifecycle states (reference: src/ray/design_docs/actor_states.rst).
ACTOR_DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
ACTOR_PENDING_CREATION = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.time)
    is_head: bool = False
    # Load view refreshed by heartbeats (reference: ray_syncer resource
    # gossip feeding ClusterResourceManager).
    available: Dict[str, float] = field(default_factory=dict)
    queued: int = 0


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: str
    class_name: str
    node_id: Optional[NodeID] = None
    worker_id: Optional[Any] = None
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: Optional[str] = None


@dataclass
class JobInfo:
    job_id: JobID
    driver_pid: int
    start_time: float
    end_time: Optional[float] = None
    entrypoint: str = ""


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    name: Optional[str]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundles: List[Dict[str, float]]
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)


class ControlState:
    """All control-plane tables behind one lock.

    Sub-tables mirror the reference's managers: kv (GcsKvManager),
    nodes (GcsNodeManager), actors (GcsActorManager), jobs
    (GcsJobManager), placement groups (GcsPlacementGroupManager), task
    events (GcsTaskManager ring buffer).
    """

    def __init__(self, task_events_max: int = 10000):
        self._lock = threading.RLock()
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> val
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name)
        self.jobs: Dict[JobID, JobInfo] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.task_events: deque = deque(maxlen=task_events_max)
        self._job_counter = 0

    # ---- kv (function blobs, cluster config) ----
    def kv_put(self, ns: str, key: str, value: bytes, overwrite=True) -> bool:
        with self._lock:
            table = self.kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            return True

    def kv_get(self, ns: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(ns, {}).get(key)

    def kv_del(self, ns: str, key: str) -> None:
        with self._lock:
            self.kv.get(ns, {}).pop(key, None)

    def kv_keys(self, ns: str, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    # ---- nodes ----
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self.nodes[info.node_id] = info

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id].last_heartbeat = time.time()

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id].alive = False

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # ---- jobs ----
    def next_job_id(self) -> JobID:
        with self._lock:
            self._job_counter += 1
            return JobID.from_int(self._job_counter)

    def add_job(self, info: JobInfo) -> None:
        with self._lock:
            self.jobs[info.job_id] = info

    def finish_job(self, job_id: JobID) -> None:
        with self._lock:
            if job_id in self.jobs:
                self.jobs[job_id].end_time = time.time()

    # ---- actors ----
    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(
                        f"Actor name {info.name!r} already taken in "
                        f"namespace {info.namespace!r}"
                    )
                self.named_actors[key] = info.actor_id
            self.actors[info.actor_id] = info

    def update_actor_state(self, actor_id: ActorID, state: str, **kw) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None:
                return
            info.state = state
            for k, v in kw.items():
                setattr(info, k, v)
            if state == ACTOR_DEAD and info.name:
                self.named_actors.pop((info.namespace, info.name), None)

    def get_named_actor(self, namespace: str, name: str) -> Optional[ActorInfo]:
        with self._lock:
            actor_id = self.named_actors.get((namespace, name))
            return self.actors.get(actor_id) if actor_id else None

    # ---- placement groups ----
    def add_placement_group(self, info: PlacementGroupInfo) -> None:
        with self._lock:
            self.placement_groups[info.pg_id] = info

    # ---- task events (observability ring buffer) ----
    def add_task_event(self, event: dict) -> None:
        with self._lock:
            self.task_events.append(event)

    def list_task_events(self, limit: int = 1000) -> List[dict]:
        with self._lock:
            return list(self.task_events)[-limit:]

    # ---- state API snapshot ----
    def summary(self) -> dict:
        with self._lock:
            return {
                "nodes": len(self.nodes),
                "alive_nodes": sum(1 for n in self.nodes.values() if n.alive),
                "actors": len(self.actors),
                "jobs": len(self.jobs),
                "placement_groups": len(self.placement_groups),
            }
