"""Single source of truth for task/actor option keys.

Reference: python/ray/_private/ray_option_utils.py — one table names
every legal ``@remote(...)`` / ``.options(...)`` key with its accepted
value shape, and both the submission path and the validators consume
it. Here the same table backs BOTH enforcement layers:

* runtime — ``validate_options()`` is called from
  ``RemoteFunction.options()`` / ``ActorClass.options()`` and the
  ``@rt.remote(...)`` decorator (``api._make_remote``), so a typo'd
  key (``num_cpu=1``) raises immediately instead of being silently
  merged and ignored by ``api_internal.submit_function``;
* static — ``ray_tpu check`` (devtools/check.py, rule RT102) imports
  the same tables to flag unknown or mistyped option keys at call
  sites without running anything.

The accepted-type tuples describe *literal* values for the static
checker; the runtime validator enforces only key membership (values
may legitimately be computed objects, e.g. scheduling strategies).
A ``None`` spec means "any value" — no literal type check.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

#: Option keys consumed by api_internal.submit_function. The spec
#: tuple lists the python types a LITERAL value may take (bool is
#: deliberately absent from numeric specs: num_cpus=True is a bug).
TASK_OPTIONS: Dict[str, Optional[Tuple[type, ...]]] = {
    "num_cpus": (int, float),
    "num_tpus": (int, float),
    "resources": (dict, type(None)),
    "num_returns": (int, str),  # ints, or "dynamic"/"streaming"
    "max_retries": (int,),
    "name": (str,),
    "scheduling_strategy": None,  # str or strategy object
    "runtime_env": (dict, type(None)),
    # internal: placement_groups.py submits its marker task with the
    # PG rewrite disabled (the marker IS the group's formatted
    # resource request).
    "_skip_pg_rewrite": (bool,),
}

#: Option keys consumed by api_internal.create_actor.
ACTOR_OPTIONS: Dict[str, Optional[Tuple[type, ...]]] = {
    "num_cpus": (int, float),
    "num_tpus": (int, float),
    "resources": (dict, type(None)),
    "name": (str,),
    "namespace": (str,),
    "max_restarts": (int,),
    "max_concurrency": (int,),
    "concurrency_groups": (dict, type(None)),
    "scheduling_strategy": None,
    "runtime_env": (dict, type(None)),
}

#: String forms num_returns accepts besides ints.
NUM_RETURNS_STRINGS = ("dynamic", "streaming")


def valid_keys(kind: str) -> Tuple[str, ...]:
    """Public (non-underscore) option keys for 'task' or 'actor'."""
    table = TASK_OPTIONS if kind == "task" else ACTOR_OPTIONS
    return tuple(sorted(k for k in table if not k.startswith("_")))


def validate_options(kind: str, options: Dict[str, Any]) -> None:
    """Reject unknown option keys with an error naming the bad key and
    the valid key set. `kind` is 'task' or 'actor'."""
    table = TASK_OPTIONS if kind == "task" else ACTOR_OPTIONS
    unknown = sorted(k for k in options if k not in table)
    if unknown:
        target = "task" if kind == "task" else "actor"
        raise ValueError(
            f"unknown {target} option key(s): {', '.join(unknown)}. "
            f"Valid {target} options: {', '.join(valid_keys(kind))}"
        )
