"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py — connects the embedded
core worker and runs the task execution loop)."""

from __future__ import annotations

import os
import sys


def main() -> None:
    socket_path = os.environ["RT_SOCKET"]
    profile_dir = os.environ.get("RT_WORKER_PROFILE")
    prof = None
    if profile_dir:
        # Startup-cost diagnosis: profile the first 2s (init + first
        # task) and dump; fork-server children skip interpreter
        # finalization, so a timer flush is the only reliable exit.
        import cProfile
        import threading

        prof = cProfile.Profile()
        prof.enable()

        def _dump():
            prof.disable()
            prof.dump_stats(
                os.path.join(
                    profile_dir, f"worker-{os.getpid()}.prof"
                )
            )

        threading.Timer(2.0, _dump).start()
    from .worker import CoreWorker, set_global_worker

    worker = CoreWorker(socket_path, role="worker")
    set_global_worker(worker)
    try:
        worker.run_task_loop()
    finally:
        worker.shutdown()


if __name__ == "__main__":
    sys.exit(main())
