"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py — connects the embedded
core worker and runs the task execution loop)."""

from __future__ import annotations

import os
import sys


def main() -> None:
    socket_path = os.environ["RT_SOCKET"]
    profile_dir = os.environ.get("RT_WORKER_PROFILE")
    prof = None
    if profile_dir:
        # Startup-cost diagnosis: profile interpreter + CoreWorker
        # init (imports, store attach, register) and dump BEFORE the
        # task loop. Same-thread enable/disable only — cProfile hooks
        # are per-thread, so a timer-thread disable would leave the
        # main thread profiled (and slowed ~2x) forever.
        import cProfile

        prof = cProfile.Profile()
        prof.enable()
    from .worker import CoreWorker, set_global_worker

    worker = CoreWorker(socket_path, role="worker")
    set_global_worker(worker)
    if prof is not None:
        prof.disable()
        prof.dump_stats(
            os.path.join(profile_dir, f"worker-{os.getpid()}.prof")
        )
    try:
        worker.run_task_loop()
    finally:
        worker.shutdown()


if __name__ == "__main__":
    sys.exit(main())
