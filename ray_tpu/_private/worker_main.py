"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py — connects the embedded
core worker and runs the task execution loop)."""

from __future__ import annotations

import os
import sys


def main() -> None:
    socket_path = os.environ["RT_SOCKET"]
    from .worker import CoreWorker, set_global_worker

    worker = CoreWorker(socket_path, role="worker")
    set_global_worker(worker)
    try:
        worker.run_task_loop()
    finally:
        worker.shutdown()


if __name__ == "__main__":
    sys.exit(main())
