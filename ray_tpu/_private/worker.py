"""CoreWorker — the per-process runtime for drivers and workers.

Plays the role of the reference's C++ CoreWorker (reference:
src/ray/core_worker/core_worker.h:162 — SubmitTask, CreateActor:876,
SubmitActorTask:930, Put:462, Get:646, Wait:685 — bound into Python via
python/ray/_raylet.pyx:2949). One instance per process; drivers use the
submit/get surface, workers additionally run the task execution loop
(reference: CoreWorkerProcess::RunTaskExecutionLoop,
core_worker_process.h:98).

Differences from the reference by design: small objects and task specs
flow through the node daemon instead of worker-to-worker gRPC (single
socket hop on-node), while large objects go straight into shared
memory and only seal notifications hit the daemon.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions as exc
from ..object_ref import ObjectRef
from .config import Config
from .function_manager import FunctionManager
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from .object_store import make_store
from .rpc import RpcClient, RpcError
from .serialization import SerializationContext
from .task_spec import (
    make_error_payload,
    make_exception_payload,
    raise_from_payload,
)

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()

#: Marker used to ship kwargs as a trailing positional arg (specs carry
#: a flat arg list; see api_internal._flatten_args).
KWARGS_MARKER = "__kwargs__"


def _split_kwargs(flat):
    if (
        flat
        and isinstance(flat[-1], tuple)
        and len(flat[-1]) == 2
        and flat[-1][0] == KWARGS_MARKER
    ):
        return list(flat[:-1]), dict(flat[-1][1])
    return list(flat), {}


def global_worker() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(worker: Optional["CoreWorker"]) -> None:
    global _global_worker
    with _global_lock:
        _global_worker = worker


class _TaskContext(threading.local):
    """Per-thread submission context. Each driver thread gets its own
    base task id so concurrent threads can't derive colliding task/put
    ids (the reference gives non-main threads random TaskIDs too)."""

    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.thread_base_id: TaskID = TaskID.from_random()
        self.put_index = 0
        self.submit_index = 0
        # Placement-group capture context of the currently executing
        # task: child submits inherit it (reference: actor.py:890
        # placement_group_capture_child_tasks).
        self.pg_context: Optional[dict] = None


_worker_generation = itertools.count()


class CoreWorker:
    def __init__(self, socket_path: str, role: str = "driver"):
        self.role = role
        # Unique per-process token for session-scoped caches (unlike
        # id(), never reused after this worker is collected).
        self.generation = next(_worker_generation)
        # Execution state must exist before the RPC client starts its
        # reader thread: the daemon may push execute_task immediately
        # after (even before) the register reply.
        self._task_queue: "queue.Queue[dict]" = queue.Queue()
        self._actor_instance: Any = None
        self._actor_id: Optional[ActorID] = None
        self._actor_pg_context: Optional[dict] = None
        self._running = True
        self._client = RpcClient(socket_path, push_handler=self._on_push)
        reply = self._client.call(
            "register_client",
            role=role,
            pid=os.getpid(),
            is_tpu=os.environ.get("RT_WORKER_TPU") == "1",
        )
        self.node_id = NodeID(reply["node_id"])
        self.config = Config(**reply["config"])
        if role == "driver":
            self.job_id = JobID(reply["job_id"])
            self.worker_id = WorkerID.from_random()
        else:
            self.job_id = JobID.from_int(0)
            self.worker_id = WorkerID(reply["worker_id"])
        self.store = make_store(
            self.node_id.hex(),
            reply["store_capacity"],
            on_evict=self._notify_store_evict,
            use_native=self.config.use_native_object_store,
        )
        self.serialization = SerializationContext(ref_class=ObjectRef)
        self.functions = FunctionManager(self._client)
        self._ctx = _TaskContext()
        self._ref_counts: Dict[ObjectID, int] = {}
        self._ref_lock = threading.Lock()

    def _notify_store_evict(self, oid: ObjectID) -> None:
        """Arena evictions can originate in any process; tell the node
        daemon so its object table stays truthful."""
        try:
            self._client.notify("object_evicted", oid=oid.binary())
        except Exception:
            pass

    # ------------------------------------------------------------------
    # reference counting (local handle counts -> daemon refcount)
    # ------------------------------------------------------------------
    def add_local_ref(self, oid: ObjectID) -> None:
        with self._ref_lock:
            self._ref_counts[oid] = self._ref_counts.get(oid, 0) + 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        if not self._running:
            return
        with self._ref_lock:
            count = self._ref_counts.get(oid, 0) - 1
            if count <= 0:
                self._ref_counts.pop(oid, None)
                notify = True
            else:
                self._ref_counts[oid] = count
                notify = False
        if notify:
            try:
                self._client.notify("del_ref", oids=[oid.binary()])
            except Exception:
                pass

    def notify_borrowed_ref(self, oid: ObjectID) -> None:
        self._client.notify("add_ref", oids=[oid.binary()])

    # ------------------------------------------------------------------
    # ids
    # ------------------------------------------------------------------
    def _current_task_id(self) -> TaskID:
        return self._ctx.task_id or self._ctx.thread_base_id

    def _next_task_id(self) -> TaskID:
        self._ctx.submit_index += 1
        return TaskID.for_task(
            self.job_id, self._current_task_id(), self._ctx.submit_index
        )

    def _next_put_id(self) -> ObjectID:
        self._ctx.put_index += 1
        return ObjectID.for_put(self._current_task_id(), self._ctx.put_index)

    # ------------------------------------------------------------------
    # object plane
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        oid = self._next_put_id()
        self.put_object(oid, value)
        return ObjectRef(oid, owner=self)

    def put_object(self, oid: ObjectID, value: Any) -> Tuple[str, Any]:
        """Serialize and store; returns ("inline", bytes) or ("shm", size)."""
        serialized = self.serialization.serialize(value)
        size = serialized.total_size()
        if size <= self.config.max_direct_call_object_size:
            data = serialized.to_bytes()
            self._client.call("put_inline", oid=oid.binary(), data=data)
            return ("inline", data)
        buf = self.store.create(oid, size)
        used = serialized.write_to(buf)
        self.store.seal(oid)
        self._client.call("object_sealed", oid=oid.binary(), size=used)
        return ("shm", used)

    def get(
        self, refs: Sequence[ObjectRef], timeout: Optional[float] = None
    ) -> List[Any]:
        deadline = None if timeout is None else time.time() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {ref}"
                )
            out.append(self._get_one(ref.id(), remaining))
        return out

    def _get_one(self, oid: ObjectID, timeout: Optional[float]) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        try:
            reply = self._client.call(
                "get_object", oid=oid.binary(), timeout=timeout
            )
        except RpcError as e:
            if "__timeout__" in str(e):
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid}"
                ) from None
            raise
        if "error" in reply and reply["error"] is not None:
            raise_from_payload(reply["error"])
        if reply.get("inline") is not None:
            return self.serialization.deserialize(reply["inline"])
        size = reply["shm_size"]
        # Sealed objects are immutable (plasma semantics): readers get
        # read-only views, so zero-copy numpy arrays can't corrupt them.
        if not getattr(self.store, "needs_release", False):
            view = self.store.get(oid, timeout=0.001)
            if view is None:
                view = self.store.open_remote(oid, size)
            return self.serialization.deserialize(view[:size].toreadonly())
        # Native arena: acquire() pins the slot. The pin must outlive
        # every zero-copy buffer carved from it — not just the fetched
        # container — so each out-of-band buffer is wrapped in a
        # _TrackedBuffer holding a shared token whose finalizer drops
        # the pin (plasma ties Release to buffer destruction the same
        # way). Values with no out-of-band buffers release immediately.
        import weakref

        from .object_store import (
            TRACKED_BUFFERS_SUPPORTED,
            _PinToken,
            _TrackedBuffer,
        )

        pin = self._acquire_arena_pin(oid, deadline)
        token = _PinToken()
        wrapped = 0

        def wrap(mv: memoryview):
            if not TRACKED_BUFFERS_SUPPORTED:
                # Pre-3.12: no PEP 688, so pin lifetime can't follow
                # the buffer — copy out of the arena (correct, not
                # zero-copy) and let the pin release immediately.
                return bytes(mv)
            nonlocal wrapped
            wrapped += 1
            return _TrackedBuffer(mv, token)

        try:
            value = self.serialization.deserialize(
                pin.view[:size].toreadonly(), buffer_wrap=wrap
            )
        except BaseException:
            pin.release()
            raise
        if wrapped:
            weakref.finalize(token, pin.release)
        else:
            pin.release()
        return value

    def _acquire_arena_pin(self, oid: ObjectID, deadline: Optional[float]):
        """Wait for `oid` to be sealed in the local arena, respecting
        the caller's get() deadline (shared with the daemon RPC, not
        granted afresh). With no deadline, block like the get()
        contract demands — but re-ask the daemon periodically so an
        eviction mid-wait triggers re-pull/reconstruction rather than
        a silent hang."""
        while True:
            remaining = (
                None if deadline is None else deadline - time.time()
            )
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid}"
                )
            slice_t = 5.0 if remaining is None else min(remaining, 5.0)
            pin = self.store.acquire(oid, timeout=slice_t)
            if pin is not None:
                return pin
            # Not local yet: nudge the daemon (re-pulls lost copies,
            # kicks lineage reconstruction if every copy died).
            try:
                self._client.call(
                    "get_object", oid=oid.binary(), timeout=remaining
                )
            except RpcError as e:
                if "__timeout__" in str(e):
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}"
                    ) from None
                raise

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if not refs:
            return [], []
        by_id = {r.binary(): r for r in refs}
        reply = self._client.call(
            "wait_objects",
            oids=[r.binary() for r in refs],
            num_returns=num_returns,
            wait_timeout=timeout,
            timeout=None if timeout is None else timeout + 10.0,
        )
        ready = [by_id[b] for b in reply["ready"] if b in by_id]
        remaining = [by_id[b] for b in reply["remaining"] if b in by_id]
        return ready, remaining

    # ------------------------------------------------------------------
    # task submission
    # ------------------------------------------------------------------
    def _serialize_args(self, args: Sequence[Any]) -> List[tuple]:
        out = []
        for arg in args:
            if isinstance(arg, ObjectRef):
                out.append(("ref", arg.binary()))
                continue
            serialized = self.serialization.serialize(arg)
            size = serialized.total_size()
            if size <= self.config.max_direct_call_object_size:
                out.append(("inline", serialized.to_bytes()))
            else:
                # Large plain arg: promoted to a put + ref (reference:
                # DependencyResolver inlining threshold).
                oid = self._next_put_id()
                buf = self.store.create(oid, size)
                used = serialized.write_to(buf)
                self.store.seal(oid)
                self._client.call(
                    "object_sealed", oid=oid.binary(), size=used
                )
                out.append(("ref", oid.binary()))
        return out

    def submit_task(
        self,
        func_key: str,
        args: Sequence[Any],
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        scheduling_strategy: Optional[dict] = None,
        pg_context: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        task_id = self._next_task_id()
        returns = [
            ObjectID.for_return(task_id, i + 1) for i in range(num_returns)
        ]
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "normal",
            "name": name,
            "function_key": func_key,
            "args": self._serialize_args(args),
            "returns": [r.binary() for r in returns],
            "resources": resources or {"CPU": 1.0},
            "max_retries": max_retries,
            "scheduling_strategy": scheduling_strategy,
            "pg_context": pg_context,
            "runtime_env": runtime_env,
        }
        self._client.call("submit_task", spec=spec)
        return [ObjectRef(r, owner=self) for r in returns]

    def create_actor(
        self,
        class_key: str,
        args: Sequence[Any],
        class_name: str,
        name: Optional[str] = None,
        namespace: str = "default",
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        handle_meta: Optional[dict] = None,
        scheduling_strategy: Optional[dict] = None,
        pg_context: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "actor_creation",
            "name": name,
            "namespace": namespace,
            "class_name": class_name,
            "function_key": class_key,
            "args": self._serialize_args(args),
            "returns": [ObjectID.for_return(task_id, 1).binary()],
            "resources": resources or {"CPU": 1.0},
            "actor_id": actor_id.binary(),
            "max_restarts": max_restarts,
            "handle_meta": handle_meta,
            "scheduling_strategy": scheduling_strategy,
            "pg_context": pg_context,
            "runtime_env": runtime_env,
        }
        self._client.call("create_actor", spec=spec)
        return actor_id

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method: str,
        args: Sequence[Any],
        num_returns: int = 1,
        max_retries: int = 0,
    ) -> List[ObjectRef]:
        task_id = self._next_task_id()
        returns = [
            ObjectID.for_return(task_id, i + 1) for i in range(num_returns)
        ]
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "actor_task",
            "name": method,
            "method": method,
            "function_key": "",
            "args": self._serialize_args(args),
            "returns": [r.binary() for r in returns],
            "resources": {},
            "actor_id": actor_id.binary(),
            "max_retries": max_retries,
        }
        self._client.call("submit_actor_task", spec=spec)
        return [ObjectRef(r, owner=self) for r in returns]

    # ------------------------------------------------------------------
    # misc API
    # ------------------------------------------------------------------
    def call(self, method: str, **kwargs) -> dict:
        return self._client.call(method, **kwargs)

    def notify(self, method: str, **kwargs) -> None:
        self._client.notify(method, **kwargs)

    # ------------------------------------------------------------------
    # worker-role execution loop
    # ------------------------------------------------------------------
    def _on_push(self, channel: str, msg: dict) -> None:
        if channel == "execute_task":
            self._task_queue.put(msg["spec"])
        elif channel == "exit":
            self._running = False
            self._task_queue.put(None)

    def current_pg_context(self) -> Optional[dict]:
        """Capturing-placement-group context of the task this thread is
        executing, if any."""
        return getattr(self._ctx, "pg_context", None)

    def run_task_loop(self) -> None:
        """Blocking execution loop (reference:
        CoreWorkerProcess::RunTaskExecutionLoop)."""
        while self._running:
            spec = self._task_queue.get()
            if spec is None:
                return
            self._execute(spec)

    def _execute(self, spec: dict) -> None:
        task_id = TaskID(spec["task_id"])
        self._ctx.task_id = task_id
        self._ctx.put_index = 0
        self._ctx.submit_index = 0
        # Actor methods inherit the capture context the actor was
        # created with (the creation spec carried it).
        self._ctx.pg_context = spec.get("pg_context") or (
            self._actor_pg_context if spec["kind"] == "actor_task" else None
        )
        self.job_id = JobID(spec["job_id"])
        try:
            from .runtime_env import apply_runtime_env

            args, kwargs = _split_kwargs(self._deserialize_args(spec["args"]))
            kind = spec["kind"]
            # Actors keep their runtime env for life (they pin this
            # worker); shared task workers restore afterwards.
            with apply_runtime_env(
                spec.get("runtime_env"),
                self,
                restore=(kind != "actor_creation"),
            ):
                if kind == "actor_creation":
                    cls = self.functions.fetch(spec["function_key"])
                    self._actor_instance = cls(*args, **kwargs)
                    self._actor_id = ActorID(spec["actor_id"])
                    self._actor_pg_context = spec.get("pg_context")
                    results = [None]
                elif kind == "actor_task":
                    if self._actor_instance is None:
                        raise exc.ActorDiedError("actor instance missing")
                    if spec["method"] == "__rt_dag_loop__":
                        # Compiled-DAG execution loop: the actor blocks
                        # on its channels until torn down
                        # (dag/compiled.py).
                        from ..dag.compiled import dag_exec_loop

                        value = dag_exec_loop(
                            self._actor_instance, *args, **kwargs
                        )
                    else:
                        method = getattr(
                            self._actor_instance, spec["method"]
                        )
                        value = method(*args, **kwargs)
                    results = self._split_returns(
                        value, len(spec["returns"])
                    )
                else:
                    func = self.functions.fetch(spec["function_key"])
                    value = func(*args, **kwargs)
                    results = self._split_returns(
                        value, len(spec["returns"])
                    )
        except BaseException as e:  # noqa: BLE001 — any task failure
            payload = make_exception_payload(e)
            self._client.notify(
                "task_done",
                task_id=spec["task_id"],
                error=payload,
                system_error=False,
            )
            return
        finally:
            self._ctx.task_id = None
            self._ctx.pg_context = None
        try:
            for oid_bytes, value in zip(spec["returns"], results):
                self.put_object(ObjectID(oid_bytes), value)
        except BaseException as e:  # noqa: BLE001
            self._client.notify(
                "task_done",
                task_id=spec["task_id"],
                error=make_error_payload(
                    "TaskError", f"failed to store results: {e!r}"
                ),
                system_error=False,
            )
            return
        self._client.notify("task_done", task_id=spec["task_id"], error=None)

    def _deserialize_args(self, wire_args: List[tuple]) -> List[Any]:
        args = []
        for kind, payload in wire_args:
            if kind == "inline":
                args.append(self.serialization.deserialize(payload))
            else:
                args.append(self._get_one(ObjectID(payload), timeout=None))
        return args

    @staticmethod
    def _split_returns(value: Any, num_returns: int) -> List[Any]:  # noqa: D102
        if num_returns == 1:
            return [value]
        if not isinstance(value, (tuple, list)) or len(value) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{type(value).__name__}"
            )
        return list(value)

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._running = False
        try:
            self._client.close()
        except Exception:
            pass
        self.store.shutdown(unlink=False)
