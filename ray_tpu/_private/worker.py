"""CoreWorker — the per-process runtime for drivers and workers.

Plays the role of the reference's C++ CoreWorker (reference:
src/ray/core_worker/core_worker.h:162 — SubmitTask, CreateActor:876,
SubmitActorTask:930, Put:462, Get:646, Wait:685 — bound into Python via
python/ray/_raylet.pyx:2949). One instance per process; drivers use the
submit/get surface, workers additionally run the task execution loop
(reference: CoreWorkerProcess::RunTaskExecutionLoop,
core_worker_process.h:98).

Differences from the reference by design: small objects and task specs
flow through the node daemon instead of worker-to-worker gRPC (single
socket hop on-node), while large objects go straight into shared
memory and only seal notifications hit the daemon.
"""

from __future__ import annotations

import contextvars
import inspect
import itertools
import os
import queue
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions as exc
from ..devtools.lock_witness import make_lock
from ..object_ref import ObjectRef
from .config import Config
from .flight_recorder import recorder as _flight
from .function_manager import FunctionManager
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from .object_store import ObjectStoreFullError, make_store
from .rpc import RpcClient, RpcError
from .serialization import SerializationContext
from .task_spec import (
    make_error_payload,
    make_exception_payload,
    raise_from_payload,
)

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()  # rt: noqa[RT004] — held for one pointer swap; forked children re-init the worker

#: Marker used to ship kwargs as a trailing positional arg (specs carry
#: a flat arg list; see api_internal._flatten_args).
KWARGS_MARKER = "__kwargs__"

#: Reusable stateless context for tasks with no runtime env (the
#: overwhelming hot path): nullcontext holds no per-entry state, so
#: one instance serves every task.
import contextlib as _contextlib  # noqa: E402

_NULL_CTX = _contextlib.nullcontext()

#: The anonymous session namespace (reference: ray's job config uses
#: an empty/anonymous namespace unless ray.init(namespace=...) names
#: one). Named here once; everywhere else resolves through the
#: session/job context rather than repeating the literal (RT006).
DEFAULT_NAMESPACE = "default"


def _split_kwargs(flat):
    if (
        flat
        and isinstance(flat[-1], tuple)
        and len(flat[-1]) == 2
        and flat[-1][0] == KWARGS_MARKER
    ):
        return list(flat[:-1]), dict(flat[-1][1])
    return list(flat), {}


#: Task identity inside async actor coroutines (thread-locals don't
#: cross onto the shared event-loop thread; see _run_coroutine).
_ASYNC_TASK_ID: contextvars.ContextVar = contextvars.ContextVar(
    "rt_async_task_id", default=None
)


_current_span_context = None


def _trace_ctx() -> Optional[dict]:
    """Current span context for remote propagation (reference: ray's
    OTel integration injects the span context into task metadata)."""
    global _current_span_context
    if _current_span_context is None:  # one-time import, off hot path
        from ..util.tracing import current_span_context

        _current_span_context = current_span_context
    ctx = _current_span_context()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def global_worker() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(worker: Optional["CoreWorker"]) -> None:
    global _global_worker
    with _global_lock:
        _global_worker = worker


class _TaskContext(threading.local):
    """Per-thread submission context. Each driver thread gets its own
    base task id so concurrent threads can't derive colliding task/put
    ids (the reference gives non-main threads random TaskIDs too)."""

    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.thread_base_id: TaskID = TaskID.from_random()
        self.put_index = 0
        self.submit_index = 0
        # Placement-group capture context of the currently executing
        # task: child submits inherit it (reference: actor.py:890
        # placement_group_capture_child_tasks).
        self.pg_context: Optional[dict] = None
        #: Set by _serialize_ref_arg when the spec being built carries
        #: a still-pending direct result as an arg — such specs must
        #: ride their own frame (see direct._Pending.solo).
        self.pending_direct_dep = False
        #: Name of the task CLASS currently executing on this thread
        #: ("" on the driver): get-provenance aggregates key on it so
        #: the doctor can convict a misplaced task class, never an id.
        self.task_name = ""


_worker_generation = itertools.count()


class _BatchReply:
    """Streams per-spec outcomes of one `execute_tasks` frame back to
    the submitter. Outcomes accumulate and flush as PARTIAL reply
    frames (`_part=True`, callback stays registered client-side) when
    64 pile up, when the owning worker's 2ms batch flusher fires, or
    — final frame, no `_part` — when the last spec completes. Eager
    flushing is what keeps a batch from head-of-line-blocking its own
    results: a quick spec's outcome reaches the driver (and its
    `wait()`ers) within ~2ms even while a slow spec later in the same
    frame is still running. Sends happen INSIDE the lock so the final
    frame can never overtake a straggling partial on the socket."""

    __slots__ = ("_conn", "_mid", "_pending", "_remaining", "_lock",
                 "_flusher")

    FLUSH_COUNT = 64

    def __init__(self, conn, mid, n: int, flusher=None):
        self._conn = conn
        self._mid = mid
        self._pending: List[tuple] = []
        self._remaining = n
        self._lock = threading.Lock()
        self._flusher = flusher

    def slot(self, index: int) -> "_BatchSlot":
        return _BatchSlot(self, index)

    def _complete(self, index: int, payload: dict) -> None:
        arm = False
        with self._lock:
            self._pending.append((index, payload))
            self._remaining -= 1
            done = self._remaining == 0
            if done:
                parts, self._pending = self._pending, []
                self._conn.reply(self._mid, {"parts": parts})
            elif len(self._pending) >= self.FLUSH_COUNT:
                parts, self._pending = self._pending, []
                self._conn.reply(
                    self._mid, {"parts": parts, "_part": True}
                )
            else:
                arm = True
        if done and self._flusher is not None:
            self._flusher.forget(self)
        elif arm and self._flusher is not None:
            self._flusher.arm(self)

    def flush_partial(self) -> None:
        """Timer-driven flush of whatever has completed so far."""
        with self._lock:
            if not self._pending or self._remaining == 0:
                return
            parts, self._pending = self._pending, []
            self._conn.reply(self._mid, {"parts": parts, "_part": True})


class _BatchSlot:
    """reply_to handle for one spec inside a batch: quacks like the
    (conn, mid) deferred-reply pair `_execute` already services."""

    __slots__ = ("_batch", "_index")

    def __init__(self, batch: _BatchReply, index: int):
        self._batch = batch
        self._index = index

    def reply(self, payload: dict) -> None:
        self._batch._complete(self._index, payload)


class _BatchFlusher:
    """One parked thread per worker process flushing batches whose
    outcomes sit pending behind a long-running spec: armed on the
    first unflushed outcome, it wakes ~2ms later and ships whatever
    has completed. Idle (parked on the event) whenever inline flushes
    keep up — the nop-flood hot path never pays for it."""

    def __init__(self):
        self._evt = threading.Event()
        self._lock = threading.Lock()
        self._armed: set = set()
        self._thread: Optional[threading.Thread] = None

    def arm(self, batch: _BatchReply) -> None:
        with self._lock:
            self._armed.add(batch)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="rt-batch-flusher",
                )
                self._thread.start()
        self._evt.set()

    def forget(self, batch: _BatchReply) -> None:
        with self._lock:
            self._armed.discard(batch)

    def _loop(self) -> None:
        while True:
            self._evt.wait()  # rt: noqa[RT008] — deliberate park; arm() sets the event
            self._evt.clear()
            time.sleep(0.002)
            with self._lock:
                batches, self._armed = list(self._armed), set()
            for batch in batches:
                try:
                    batch.flush_partial()
                except Exception:
                    pass


class CoreWorker:
    def __init__(self, socket_path: str, role: str = "driver"):
        self.role = role
        #: Default namespace for named-actor APIs in THIS process.
        #: The driver's is set from rt.init(namespace=...); worker
        #: processes inherit the submitting driver's namespace through
        #: the task/actor spec (`ns_ctx`, applied in _execute) so
        #: in-task get_actor()/named-actor creation resolves against
        #: the session namespace (reference: the job config propagates
        #: ray_namespace to every worker of the job). The explicit
        #: namespace= escape hatch on the APIs remains.
        self.namespace = DEFAULT_NAMESPACE
        #: Namespace context the actor hosted by this worker was
        #: created under; actor tasks restore it (actors keep their
        #: creating job's namespace for life).
        self._actor_namespace: Optional[str] = None
        # Unique per-process token for session-scoped caches (unlike
        # id(), never reused after this worker is collected).
        self.generation = next(_worker_generation)
        # Execution state must exist before the RPC client starts its
        # reader thread: the daemon may push execute_task immediately
        # after (even before) the register reply.
        self._task_queue: "queue.Queue" = queue.Queue()
        #: task_id hex -> {name, kind, started} for every task this
        #: process is executing right now (concurrent actors may hold
        #: several). Read by the `inspect` direct handler — the
        #: doctor's pull-based hung-task scan.
        self._inflight_tasks: Dict[str, dict] = {}
        self._actor_instance: Any = None
        self._actor_id: Optional[ActorID] = None
        self._actor_pg_context: Optional[dict] = None
        self._actor_pool = None  # ThreadPoolExecutor, max_concurrency>1
        #: name -> ThreadPoolExecutor for named concurrency groups.
        self._actor_group_pools: Dict[str, Any] = {}
        self._actor_loop = None  # asyncio loop thread for async methods
        self._actor_loop_lock = threading.Lock()
        self._running = True
        # Direct task transport (reference: normal_task_submitter.cc
        # worker-to-worker task push). Workers serve a tiny RPC
        # endpoint; drivers lease workers and push specs straight to
        # it, with results inline in the reply (_private/direct.py).
        self._direct_server = None
        direct_address = None
        if role == "worker":
            from .rpc import DEFERRED, RpcServer

            session_dir = os.path.dirname(os.path.abspath(socket_path))
            direct_address = os.path.join(
                session_dir, f"dworker-{os.getpid()}.sock"
            )
            self._direct_server = RpcServer(direct_address)

            def _h_direct_execute(conn, msg):
                self._task_queue.put((msg["spec"], (conn, msg["_mid"])))
                return DEFERRED

            self._batch_flusher = _BatchFlusher()
            self._reclaim_evt = threading.Event()
            threading.Thread(
                target=self._batch_reclaim_loop, daemon=True,
                name="rt-batch-reclaim",
            ).start()

            def _h_direct_execute_tasks(conn, msg):
                # Batched submission: one frame carries N flat-codec
                # spec blobs; specs enqueue in order and outcomes
                # stream back as partial reply frames. Error isolation
                # lives in the outcome slots, not the envelope — a
                # blob that fails decode (codec skew after a rolling
                # upgrade) fails ONLY its own slot; the rest of the
                # frame executes.
                from .wire import (
                    SpecCodecError,
                    decode_spec,
                    split_spec_batch,
                )

                blobs = split_spec_batch(msg["specs"])
                batch = _BatchReply(
                    conn, msg["_mid"], len(blobs),
                    flusher=self._batch_flusher,
                )
                put = self._task_queue.put
                for i, blob in enumerate(blobs):
                    try:
                        spec = decode_spec(blob)
                    except SpecCodecError as e:
                        batch.slot(i).reply({"error": make_error_payload(
                            "TaskError", f"undecodable spec blob: {e}"
                        )})
                        continue
                    put((spec, batch.slot(i)))
                self._reclaim_evt.set()
                return DEFERRED

            # Inline dispatch: both handlers only queue.put, so they
            # run on the hub thread — the spec reaches the task loop
            # with ONE thread wakeup instead of two (hub -> pool ->
            # loop). Lease connections carry nothing that orders
            # against these frames.
            self._direct_server.register(
                "execute_task", _h_direct_execute, inline=True
            )
            self._direct_server.register(
                "execute_tasks", _h_direct_execute_tasks, inline=True
            )
            self._direct_server.register("ping", lambda conn, msg: {})

            def _h_profile(conn, msg):
                # Long-running by design (a cpu profile sleeps for its
                # whole window): run on a dedicated thread and reply
                # deferred so the RPC hub never blocks (reference:
                # profile_manager.py attaches py-spy out-of-band).
                mid = msg["_mid"]

                def run():
                    try:
                        from .profiling import run_profile

                        params = {
                            k: msg[k]
                            for k in (
                                "duration_s", "hz", "top", "start_at",
                            )
                            if k in msg
                        }
                        result = run_profile(
                            msg.get("kind", "stack"), **params
                        )
                        conn.reply(mid, result)
                    except Exception as e:  # noqa: BLE001 — to caller
                        conn.reply(mid, {"_error": repr(e)})

                threading.Thread(
                    target=run, daemon=True, name="rt-profiler"
                ).start()
                return DEFERRED

            self._direct_server.register("profile", _h_profile)

            def _h_inspect(conn, msg):
                # Pull-based liveness introspection: what is THIS
                # worker executing right now, and for how long? The
                # doctor's hung-task scan reads this instead of the
                # task-event stream (direct-transport tasks report
                # events only at completion — an in-flight hang is
                # invisible there by design).
                now = time.time()
                return {
                    "pid": os.getpid(),
                    "inflight": [
                        dict(
                            info,
                            age_s=round(now - info["started"], 3),
                        )
                        for info in list(
                            self._inflight_tasks.values()
                        )
                    ],
                    "queued": self._task_queue.qsize(),
                }

            self._direct_server.register("inspect", _h_inspect)

            def _h_flight_recorder(conn, msg):
                rec = _flight()
                return {
                    "pid": os.getpid(),
                    "records": rec.snapshot(
                        limit=msg.get("limit", 0),
                        kinds=msg.get("kinds"),
                    ),
                    "summary": rec.summary(),
                }

            self._direct_server.register(
                "flight_recorder", _h_flight_recorder
            )

            def _h_lock_witness(conn, msg):
                from ray_tpu.devtools.lock_witness import snapshot

                return snapshot()

            self._direct_server.register(
                "lock_witness", _h_lock_witness
            )
            self._direct_server.start()
        self._direct_task_counts = {
            "lock": make_lock("worker.direct_counts"),
            "finished": 0,
            "failed": 0,
            "events": [],
            "last_flush": 0.0,
        }
        # Workers give the daemon a LONG connect window: on an
        # overloaded box (10k-actor waves) the daemon's accept thread
        # can go unscheduled for tens of seconds, and a worker that
        # gives up at the default 10s counts as a startup crash —
        # three of those nuke the whole task queue.
        self._client = RpcClient(
            socket_path,
            push_handler=self._on_push,
            connect_timeout=float(
                os.environ.get("RT_WORKER_CONNECT_TIMEOUT", "60")
            )
            if role == "worker"
            else 10.0,
        )
        reply = self._client.call(
            "register_client",
            role=role,
            pid=os.getpid(),
            is_tpu=os.environ.get("RT_WORKER_TPU") == "1",
            direct_address=direct_address,
        )
        self.node_id = NodeID(reply["node_id"])
        self.config = Config(**reply["config"])
        from .compile_watch import configure as _compile_configure
        from .flight_recorder import configure as _flight_configure
        from ray_tpu.devtools.lock_witness import (
            configure as _witness_configure,
        )

        _flight_configure(self.config)
        _compile_configure(self.config)
        _witness_configure(self.config)
        if role == "driver":
            self.job_id = JobID(reply["job_id"])
            self.worker_id = WorkerID.from_random()
        else:
            self.job_id = JobID.from_int(0)
            self.worker_id = WorkerID(reply["worker_id"])
        self.store = make_store(
            self.node_id.hex(),
            reply["store_capacity"],
            on_evict=self._notify_store_evict,
            use_native=self.config.use_native_object_store,
            client=True,
        )
        self.serialization = SerializationContext(ref_class=ObjectRef)
        self.functions = FunctionManager(self._client)
        self._ctx = _TaskContext()
        self._ref_counts: Dict[ObjectID, int] = {}
        # RLock: remove_local_ref runs from ObjectRef.__del__, which
        # the cyclic GC can fire during an allocation made while this
        # lock is already held on the same thread.
        self._ref_lock = threading.RLock()
        #: Owner-side cache of small put() values (serialized): local
        #: gets never leave the process; the daemon registration rides
        #: an async notify (same-connection FIFO keeps any dependent
        #: message ordered after it). Entries die with the local ref.
        #: (reference: CoreWorkerMemoryStore for small owned objects.)
        self._inline_cache: Dict[ObjectID, bytes] = {}
        #: Get-provenance aggregates: (provenance, src_node, task)
        #: -> [count, bytes, wait_ms]. Drained onto the metrics pipe
        #: once per flush tick (util.metrics._Buffer drain hook) —
        #: classification happens HERE at the source, and the wire
        #: cost is one aggregate record per distinct key per tick,
        #: never a per-get RPC.
        self._get_stats: Dict[tuple, list] = {}
        self._get_stats_lock = threading.Lock()
        #: Buffer generation the drain hook is registered on (fork /
        #: shutdown build a new buffer; re-register lazily).
        self._get_stats_buf = None
        self._get_stats_drained = 0.0
        #: Batched ref-release notifications: one daemon wakeup per
        #: batch instead of one per ObjectRef GC (the wakeup cost
        #: dominates on small hosts). A parked flusher thread drains
        #: the batch ~50ms after the first drop, so deletion stays
        #: prompt without per-ref traffic.
        self._pending_dels: List[bytes] = []
        self._del_flush_evt = threading.Event()
        self._del_flusher: Optional[threading.Thread] = None
        self._direct = None
        self._actor_routers: Dict[ActorID, Any] = {}
        if role == "driver" and self.config.use_direct_calls:
            from .direct import DirectTaskManager

            self._direct = DirectTaskManager(self)
        # Daemon-path batch submission (specs the direct transport
        # can't take: strategies, TPU gangs, runtime envs, or
        # use_direct_calls=False). Kill switch: task_submit_batching.
        self._submit_pipeline = None
        if self.config.task_submit_batching:
            from .submit_queue import SubmitPipeline

            self._submit_pipeline = SubmitPipeline(self)
        if role == "driver":
            # Error events always flow (reference: published error
            # messages print regardless of log streaming); worker
            # stdout/stderr only with log_to_driver. The subscription
            # is per-connection daemon state, so it must be re-sent
            # after any transparent RPC reconnect.
            channels = ["error_event"]
            if self.config.log_to_driver:
                channels.append("log_lines")

            def _subscribe():
                self._client.notify(
                    "subscribe_logs", channels=channels
                )

            _subscribe()
            self._client.set_on_reconnect(_subscribe)

    def _notify_store_evict(self, oid: ObjectID) -> None:
        """Arena evictions can originate in any process; tell the node
        daemon so its object table stays truthful."""
        try:
            self._client.notify("object_evicted", oid=oid.binary())
        except Exception:
            pass

    # ------------------------------------------------------------------
    # reference counting (local handle counts -> daemon refcount)
    # ------------------------------------------------------------------
    def add_local_ref(self, oid: ObjectID) -> None:
        with self._ref_lock:
            self._ref_counts[oid] = self._ref_counts.get(oid, 0) + 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        if not self._running:
            return
        with self._ref_lock:
            count = self._ref_counts.get(oid, 0) - 1
            if count <= 0:
                self._ref_counts.pop(oid, None)
                self._inline_cache.pop(oid, None)
                notify = True
            else:
                self._ref_counts[oid] = count
                notify = False
        if notify:
            if self._direct is not None:
                self._direct.forget(oid)
            start_flusher = None
            with self._ref_lock:
                self._pending_dels.append(oid.binary())
                flush = len(self._pending_dels) >= 64
                if self._del_flusher is None:
                    # Construct/start outside the lock: Thread() can
                    # allocate enough to trigger GC -> __del__ ->
                    # re-entry here.
                    self._del_flusher = start_flusher = threading.Thread(
                        target=self._del_flush_loop,
                        name="rt-del-flusher",
                        daemon=True,
                    )
            if start_flusher is not None:
                start_flusher.start()
            if flush:
                self.flush_pending_dels()
            else:
                self._del_flush_evt.set()

    def _del_flush_loop(self) -> None:
        while self._running:
            self._del_flush_evt.wait()  # rt: noqa[RT008] — deliberate park; shutdown() sets the event
            self._del_flush_evt.clear()
            if not self._running:
                return
            time.sleep(0.05)  # debounce a GC burst into one notify
            self.flush_pending_dels()

    def flush_pending_dels(self) -> None:
        with self._ref_lock:
            if not self._pending_dels:
                return
            batch, self._pending_dels = self._pending_dels, []
        try:
            self._client.notify("del_ref", oids=batch)
        except Exception:
            pass

    def notify_borrowed_ref(self, oid: ObjectID) -> None:
        self._client.notify("add_ref", oids=[oid.binary()])

    # ------------------------------------------------------------------
    # ids
    # ------------------------------------------------------------------
    def _current_task_id(self) -> TaskID:
        return self._ctx.task_id or self._ctx.thread_base_id

    def _next_task_id(self) -> TaskID:
        self._ctx.submit_index += 1
        return TaskID.for_task(
            self.job_id, self._current_task_id(), self._ctx.submit_index
        )

    def _next_put_id(self) -> ObjectID:
        self._ctx.put_index += 1
        return ObjectID.for_put(self._current_task_id(), self._ctx.put_index)

    # ------------------------------------------------------------------
    # object plane
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        oid = self._next_put_id()
        self.put_object(oid, value, cache=True)
        return ObjectRef(oid, owner=self)

    def _store_create(self, oid: ObjectID, size: int) -> memoryview:
        """create() with spill-on-full: if the store can't make room by
        evicting, ask the daemon to spill cold objects to disk and retry
        (reference: plasma create retries after the raylet spills,
        create_request_queue.h). Bounded retries: under concurrent
        producers the freed space can be claimed before our retry."""
        last: Exception = None
        for attempt in range(4):
            try:
                return self.store.create(oid, size)
            except ObjectStoreFullError as e:
                last = e
                if attempt == 3:
                    break  # no retry left: don't pay one more spill
                self._client.call(
                    "spill_request", bytes_needed=size, timeout=60.0
                )
                if attempt:
                    time.sleep(0.05 * attempt)
        raise last

    def _owner_fields(self, oid: Optional[ObjectID] = None) -> dict:
        """Owner attribution riding every seal/put report (the memory
        ledger's per-job accounting): job hex plus the creating
        context — the executing actor, the executing task, or the
        driver itself — and this process's pid for node-local leak
        liveness probes. Direct-transport results are sealed after
        the task context is already cleared, so a worker process
        falls back to the creating task the oid itself embeds
        (ObjectID.for_return/for_put carry it)."""
        if self._actor_id is not None:
            owner = "actor:" + self._actor_id.hex()
        elif self._ctx.task_id is not None:
            owner = "task:" + self._ctx.task_id.hex()
        elif self.role == "worker" and oid is not None:
            owner = "task:" + oid.task_id().hex()
        else:
            owner = "driver"
        return {
            "owner_job": self.job_id.hex(),
            "owner": owner,
            "owner_pid": os.getpid(),
        }

    def _seal_and_report(self, oid: ObjectID, used: int) -> None:
        """Seal a just-written object and report it to the daemon. On
        the shared arena the seal takes a creator pin held until the
        daemon's primary pin is in place — otherwise another process's
        create() could LRU-evict the brand-new (pin-less) object in
        that window, losing the only copy."""
        pin = None
        seal_pinned = getattr(self.store, "seal_pinned", None)
        if seal_pinned is not None:
            pin = seal_pinned(oid)
        else:
            self.store.seal(oid)
        try:
            self._client.call(
                "object_sealed", oid=oid.binary(), size=used,
                **self._owner_fields(oid),
            )
        finally:
            if pin is not None:
                pin.release()

    def put_object(
        self, oid: ObjectID, value: Any, cache: bool = False
    ) -> Tuple[str, Any]:
        rec = _flight()
        if not rec.enabled:
            return self._put_object_inner(oid, value, cache)
        t0 = time.monotonic()
        try:
            kind, payload = self._put_object_inner(oid, value, cache)
        except BaseException:
            # A failed write (store full, serialization error) is
            # exactly the event the ring exists to keep — same
            # discipline as _get_one.
            rec.record(
                "store.put",
                "put",
                (time.monotonic() - t0) * 1e3,
                {"error": True},
            )
            raise
        rec.record(
            "store.put",
            kind,
            (time.monotonic() - t0) * 1e3,
            {"bytes": len(payload) if kind == "inline" else payload},
        )
        return kind, payload

    def _put_object_inner(
        self, oid: ObjectID, value: Any, cache: bool = False
    ) -> Tuple[str, Any]:
        """Serialize and store; returns ("inline", bytes) or ("shm", size).

        `cache=True` (explicit put(): an ObjectRef will hold a local
        ref whose release evicts the entry) keeps small values in the
        owner-side inline cache. Task-return storage passes False —
        no local ref exists to bound the cache."""
        serialized = self.serialization.serialize(value)
        size = serialized.total_size()
        if size <= self.config.max_direct_call_object_size:
            data = serialized.to_bytes()
            if cache:
                with self._ref_lock:
                    self._inline_cache[oid] = data
            # Async registration: the daemon's deferred-waiter get path
            # answers anyone who asks before the notify lands.
            self._client.notify(
                "put_inline", oid=oid.binary(), data=data,
                **self._owner_fields(oid),
            )
            return ("inline", data)
        # Large object: flush deferred ref-drops first so the daemon's
        # eviction view is current when space is tight.
        self.flush_pending_dels()
        buf = self._store_create(oid, size)
        used = serialized.write_to(buf)
        self._seal_and_report(oid, used)
        return ("shm", used)

    def get(
        self, refs: Sequence[ObjectRef], timeout: Optional[float] = None
    ) -> List[Any]:
        deadline = None if timeout is None else time.time() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {ref}"
                )
            out.append(self._get_one(ref.id(), remaining))
        return out

    #: Daemon ObjectEntry.source marker -> the provenance class billed
    #: to the consumer (absent marker = warm local arena hit).
    _VIA_PROVENANCE = {
        "pull": "pull",
        "pull_spill": "restore_remote",
        "restore": "restore_local",
    }

    def _record_get(
        self, provenance: str, src: str, nbytes: int, ms: float
    ) -> None:
        """Classify ONE rt.get resolution at the source and fold it
        into this process's aggregate table. O(one dict update) — this
        is the per-get cost the `get_provenance_overhead_us` bench
        bars; the wire cost is one record per distinct (provenance,
        src, task) per drain, riding the metrics flush tick. Never a
        per-get RPC."""
        if self.config.transfer_report_interval_s <= 0:
            return
        key = (provenance, src, self._ctx.task_name)
        with self._get_stats_lock:
            row = self._get_stats.get(key)
            if row is None:
                self._get_stats[key] = [1, nbytes, ms]
            else:
                row[0] += 1
                row[1] += nbytes
                row[2] += ms
        if ms > 0.0 and self._ctx.task_id is not None:
            # Bill the wait as its own step phase — only while
            # executing a task (driver-side gets between steps would
            # pollute the NEXT report_step's phase bucket with
            # unrelated wall), and only when no enclosing phase_timer
            # (data_wait, recv, ...) is already measuring this wall;
            # phases must stay a partition of the step.
            from .step_telemetry import add_phase, stalls_active

            if not stalls_active():
                add_phase("get_wait_ms", ms)
        self._ensure_get_drain()

    def _ensure_get_drain(self) -> None:
        """Register the drain hook on the CURRENT buffer generation
        (fork and shutdown drop the singleton; re-register lazily)."""
        from ..util.metrics import _Buffer

        buf = _Buffer.get()
        if self._get_stats_buf is buf:
            return
        self._get_stats_buf = buf  # rt: noqa[RT201] — add_drain_hook is idempotent; a racing duplicate registration is a no-op
        buf.add_drain_hook(self._drain_get_stats)

    def _drain_get_stats(self) -> None:
        """Pre-flush drain: push one aggregate "get" record per
        distinct key, rate-limited by `transfer_report_interval_s`."""
        now = time.monotonic()
        with self._get_stats_lock:
            if (
                now - self._get_stats_drained
                < self.config.transfer_report_interval_s
            ):
                return
            self._get_stats_drained = now
            stats, self._get_stats = self._get_stats, {}
        if not stats:
            return
        from ..util.metrics import _Buffer

        buf = _Buffer.get()
        node = self.node_id.hex()
        job = self.job_id.hex()
        for (prov, src, task), (count, nbytes, ms) in stats.items():
            buf.push(
                (
                    "get",
                    prov,
                    float(count),
                    (
                        ("bytes", str(int(nbytes))),
                        ("job", job),
                        ("ms", str(round(ms, 3))),
                        ("node", node),
                        ("src", src),
                        ("task", task),
                    ),
                )
            )

    def _get_one(self, oid: ObjectID, timeout: Optional[float]) -> Any:
        rec = _flight()
        if not rec.enabled:
            return self._get_one_inner(oid, timeout)
        with self._ref_lock:
            cached = self._inline_cache.get(oid)
        if cached is not None:
            # Inline-cache hits are sub-microsecond and arrive
            # thousands per second after a fan-out — recording each
            # would evict the diagnostic events the ring exists to
            # keep (same discipline as the daemon's zero-wait lock
            # acquisitions). Resolved right here so the hot path pays
            # ONE lock acquisition, not a probe plus the inner
            # lookup.
            self._record_get("inline", "", len(cached), 0.0)
            return self.serialization.deserialize(cached)
        t0 = time.monotonic()
        try:
            value = self._get_one_inner(oid, timeout)
        except BaseException:
            rec.record(
                "store.get",
                "fetch",
                (time.monotonic() - t0) * 1e3,
                {"error": True},
            )
            raise
        rec.record(
            "store.get", "fetch", (time.monotonic() - t0) * 1e3
        )
        return value

    def _get_one_inner(
        self, oid: ObjectID, timeout: Optional[float]
    ) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        t0 = time.monotonic()
        with self._ref_lock:
            cached = self._inline_cache.get(oid)
        if cached is not None:
            self._record_get("inline", "", len(cached), 0.0)
            return self.serialization.deserialize(cached)
        if self._direct is not None:
            entry = self._direct.lookup(oid)
            if entry is not None:
                fut, index = entry
                if not fut.wait(timeout):
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}"
                    )
                # One deadline across future-wait and whatever follows
                # (store read or daemon fallback) — not timeout twice.
                timeout = (
                    None if deadline is None else deadline - time.time()
                )
                if not fut.daemon_fallback:
                    if fut.error is not None:
                        raise_from_payload(fut.error)
                    kind, payload = fut.results[index]
                    if kind == "inline":
                        self._record_get(
                            "inline", "", len(payload),
                            (time.monotonic() - t0) * 1e3,
                        )
                        return self.serialization.deserialize(payload)
                    remaining = (
                        None if deadline is None
                        else deadline - time.time()
                    )
                    value = self._read_local_store(
                        oid, payload, remaining
                    )
                    self._record_get(
                        "local", "", int(payload),
                        (time.monotonic() - t0) * 1e3,
                    )
                    return value
                # fell back to the daemon path: ask it below
        while True:
            timeout = None if deadline is None else deadline - time.time()
            try:
                reply = self._client.call(
                    "get_object", oid=oid.binary(), timeout=timeout
                )
            except RpcError as e:
                if "__timeout__" in str(e):
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}"
                    ) from None
                raise
            if "error" in reply and reply["error"] is not None:
                raise_from_payload(reply["error"])
            if reply.get("inline") is not None:
                self._record_get(
                    "inline", "", len(reply["inline"]),
                    (time.monotonic() - t0) * 1e3,
                )
                return self.serialization.deserialize(reply["inline"])
            remaining = None if deadline is None else deadline - time.time()
            try:
                value = self._read_local_store(
                    oid, reply["shm_size"], remaining
                )
                # Classify at the source: the daemon's reply says how
                # this node's copy materialised (absent via = warm
                # local hit), so the wait bills to the right
                # provenance class without any extra round trip.
                self._record_get(
                    self._VIA_PROVENANCE.get(
                        reply.get("via"), "local"
                    ),
                    str(reply.get("src", "")),
                    int(reply["shm_size"]),
                    (time.monotonic() - t0) * 1e3,
                )
                return value
            except FileNotFoundError:
                # The daemon spilled/evicted the segment between its
                # reply and our attach; re-ask — the daemon's get path
                # restores from spill (or re-pulls/reconstructs).
                if deadline is not None and deadline - time.time() <= 0:
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}"
                    ) from None
                time.sleep(0.01)

    def peek_object_error(self, oid: ObjectID) -> Optional[bytes]:
        """Error payload of a KNOWN-READY object, or None if it holds a
        value. Lets generator consumers inspect a failed completion
        marker (e.g. for items_emitted) without raising."""
        if self._direct is not None:
            entry = self._direct.lookup(oid)
            if entry is not None:
                fut = entry[0]
                if fut.done() and not fut.daemon_fallback:
                    return fut.error
        try:
            reply = self._client.call(
                "get_object", oid=oid.binary(), timeout=30.0
            )
        except RpcError:
            return None
        return reply.get("error")

    def _read_local_store(
        self, oid: ObjectID, size: int, timeout: Optional[float]
    ) -> Any:
        """Zero-copy read of a sealed object from the node's shared
        store (segment or native arena)."""
        deadline = None if timeout is None else time.time() + timeout
        # Sealed objects are immutable (plasma semantics): readers get
        # read-only views, so zero-copy numpy arrays can't corrupt them.
        if not getattr(self.store, "needs_release", False):
            view = self.store.get(oid, timeout=0.001)
            if view is None:
                view = self.store.open_remote(oid, size)
            return self.serialization.deserialize(view[:size].toreadonly())
        # Native arena: acquire() pins the slot. The pin must outlive
        # every zero-copy buffer carved from it — not just the fetched
        # container — so its release rides the lifetime of the view's
        # PER-PIN ctypes exporter: every memoryview sliced from the
        # pinned view (numpy arrays reconstructed over out-of-band
        # buffers included) keeps that exporter alive, and a finalizer
        # on the exporter drops the pin when the last view dies
        # (plasma ties Release to buffer destruction the same way).
        # Values whose deserialization copies (or with no out-of-band
        # buffers) release immediately. This replaced the pre-3.12
        # copy-out fallback: a 64 MB get no longer pays a second
        # memcpy on any supported interpreter.
        from .object_store import transfer_pin_to_exporter

        pin = self._acquire_arena_pin(oid, deadline)
        wrapped = 0

        def wrap(mv: memoryview):
            nonlocal wrapped
            wrapped += 1
            return mv

        try:
            value = self.serialization.deserialize(
                pin.view[:size].toreadonly(), buffer_wrap=wrap
            )
        except BaseException:
            pin.release()
            raise
        if wrapped:
            transfer_pin_to_exporter(pin)
        else:
            pin.release()
        return value

    def _acquire_arena_pin(self, oid: ObjectID, deadline: Optional[float]):
        """Wait for `oid` to be sealed in the local arena, respecting
        the caller's get() deadline (shared with the daemon RPC, not
        granted afresh). With no deadline, block like the get()
        contract demands — but re-ask the daemon periodically so an
        eviction mid-wait triggers re-pull/reconstruction rather than
        a silent hang."""
        while True:
            remaining = (
                None if deadline is None else deadline - time.time()
            )
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid}"
                )
            slice_t = 5.0 if remaining is None else min(remaining, 5.0)
            pin = self.store.acquire(oid, timeout=slice_t)
            if pin is not None:
                return pin
            # Not local yet: nudge the daemon (re-pulls lost copies,
            # kicks lineage reconstruction if every copy died).
            try:
                self._client.call(
                    "get_object", oid=oid.binary(), timeout=remaining
                )
            except RpcError as e:
                if "__timeout__" in str(e):
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}"
                    ) from None
                raise

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if not refs:
            return [], []
        direct: Dict[ObjectRef, Any] = {}
        if self._direct is not None:
            for ref in refs:
                entry = self._direct.lookup(ref.id())
                if entry is not None:
                    direct[ref] = entry[0]
        if not direct:
            return self._wait_daemon(refs, num_returns, timeout)
        # Direct futures are owner-local; poll them alongside the
        # daemon set in slices (mixed sets are rare — usually a wait()
        # is all-direct, where the loop blocks on an any-completion
        # event with no daemon traffic).
        deadline = None if timeout is None else time.time() + timeout
        daemon_refs = [r for r in refs if r not in direct]
        any_done = threading.Event()

        def _on_done(_fut):
            any_done.set()

        registered = set(direct.values())
        for fut in registered:
            fut.add_done_callback(_on_done)
        try:
            return self._wait_mixed(
                refs, direct, daemon_refs, num_returns, deadline, any_done
            )
        finally:
            for fut in registered:
                fut.remove_done_callback(_on_done)

    def _wait_mixed(
        self, refs, direct, daemon_refs, num_returns, deadline, any_done
    ):
        while True:
            ready, remaining = [], []
            for ref in refs:
                fut = direct.get(ref)
                if fut is None:
                    remaining.append(ref)  # resolved via daemon below
                elif fut.daemon_fallback:
                    daemon_refs.append(ref)
                    del direct[ref]
                    remaining.append(ref)
                elif fut.done():
                    ready.append(ref)
                else:
                    remaining.append(ref)
            if daemon_refs and len(ready) < num_returns:
                d_ready, _ = self._wait_daemon(
                    daemon_refs, len(daemon_refs), 0.0
                )
                ready.extend(d_ready)
                remaining = [r for r in remaining if r not in set(d_ready)]
            if len(ready) >= num_returns:
                return ready[:num_returns], [
                    r for r in refs if r not in set(ready[:num_returns])
                ]
            now = time.time()
            if deadline is not None and now >= deadline:
                return ready, remaining
            slice_t = 0.05 if daemon_refs else (
                None if deadline is None else deadline - now
            )
            if deadline is not None and slice_t is not None:
                slice_t = min(slice_t, max(deadline - now, 0.0))
            pending = [f for f in direct.values() if not f.done()]
            if pending:
                # Any single completion wakes the wait (each future
                # sets any_done via its done-callback).
                any_done.clear()
                if any(f.done() for f in pending):
                    continue  # completed between scan and clear
                any_done.wait(slice_t)
            elif daemon_refs:
                time.sleep(min(slice_t or 0.05, 0.05))
            else:
                # everything direct is done but num_returns unreachable
                return ready, remaining

    def _wait_daemon(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        by_id = {r.binary(): r for r in refs}
        reply = self._client.call(
            "wait_objects",
            oids=[r.binary() for r in refs],
            num_returns=num_returns,
            wait_timeout=timeout,
            timeout=None if timeout is None else timeout + 10.0,
        )
        ready = [by_id[b] for b in reply["ready"] if b in by_id]
        remaining = [by_id[b] for b in reply["remaining"] if b in by_id]
        return ready, remaining

    # ------------------------------------------------------------------
    # task submission
    # ------------------------------------------------------------------
    def _serialize_args(self, args: Sequence[Any]) -> List[tuple]:
        out = []
        for arg in args:
            if isinstance(arg, ObjectRef):
                out.append(self._serialize_ref_arg(arg))
                continue
            serialized = self.serialization.serialize(arg)
            size = serialized.total_size()
            if size <= self.config.max_direct_call_object_size:
                out.append(("inline", serialized.to_bytes()))
            else:
                # Large plain arg: promoted to a put + ref (reference:
                # DependencyResolver inlining threshold).
                oid = self._next_put_id()
                buf = self._store_create(oid, size)
                used = serialized.write_to(buf)
                self._seal_and_report(oid, used)
                out.append(("ref", oid.binary()))
        return out

    def _serialize_ref_arg(self, arg: ObjectRef) -> tuple:
        """Owner-side dependency resolution for direct-call results
        (reference: normal_task_submitter.cc DependencyResolver —
        the owner waits for locally-owned results and inlines small
        ones into the dependent spec). Non-direct refs pass through."""
        if self._direct is None:
            return ("ref", arg.binary())
        entry = self._direct.lookup(arg.id())
        if entry is None:
            return ("ref", arg.binary())
        fut, index = entry
        if fut.done() and not fut.daemon_fallback:
            if fut.error is not None:
                # Publish the error to the daemon table so the
                # dependent task fails with the underlying cause.
                self._direct.ensure_published(arg.id())
                return ("ref", arg.binary())
            kind, payload = fut.results[index]
            if kind == "inline":
                return ("inline", payload)
            return ("ref", arg.binary())  # shm: worker registered it
        # Still pending (or daemon-owned): never block submission —
        # pass the ref through and publish the result to the daemon's
        # object table when it lands, so the executing worker's fetch
        # resolves (chains stay pipelined; reference: the owner
        # resolves dependencies asynchronously, dependency_resolver.cc).
        # The dependent spec must ship in its own frame: batched
        # behind other specs, its in-worker wait could deadlock
        # against the very reply that publishes this result.
        self._ctx.pending_direct_dep = True
        self._direct.publish_when_done(arg.id())
        return ("ref", arg.binary())

    def ensure_globally_visible(self, oid: ObjectID) -> None:
        """Called when a ref escapes this process (pickled into a
        value or borrowed): direct inline results must reach the
        daemon's object table first or the borrower can never resolve
        them."""
        if self._direct is not None:
            try:
                self._direct.ensure_published(oid)
            except Exception:
                pass

    @staticmethod
    def _prune_spec(spec: dict) -> dict:
        """Drop None-valued optional fields before a spec enters the
        submit queues (absent == None for every .get() consumer; the
        dead entries cost ~100 B/task at the 1M-queue scale). Used on
        the COLD actor paths; the task hot path builds its spec
        without the second pass."""
        return {k: v for k, v in spec.items() if v is not None}

    def submit_task(
        self,
        func_key: str,
        args: Sequence[Any],
        name: str = "",
        num_returns=1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        scheduling_strategy: Optional[dict] = None,
        pg_context: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        task_id = self._next_task_id()
        # Generator tasks ("dynamic"/"streaming") have ONE declared
        # return — the completion marker; item ids are deterministic
        # (object_ref.ObjectRefGenerator).
        mode = num_returns if isinstance(num_returns, str) else None
        n_declared = 1 if mode else num_returns
        returns = [
            ObjectID.for_return(task_id, i + 1) for i in range(n_declared)
        ]
        self._ctx.pending_direct_dep = False
        wire_args = self._serialize_args(args)
        # Optional fields enter the spec only when set: every consumer
        # reads them via .get() (absent == None), and at the 1M-queued
        # scale the dead entries cost ~100 B/task of driver+head RSS.
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "normal",
            "name": name,
            "function_key": func_key,
            "args": wire_args,
            "returns": [r.binary() for r in returns],
            # `resources={}` is a real request (zero-resource task; the
            # reference schedules these anywhere, ray_option_utils.py
            # num_cpus=0) — only None means "caller didn't resolve
            # options" and gets the 1-CPU default.
            "resources": (
                resources if resources is not None else {"CPU": 1.0}
            ),
            "max_retries": max_retries,
        }
        if self.namespace != DEFAULT_NAMESPACE:
            # Session-namespace context: the executing worker adopts it
            # so nested named-actor APIs resolve against the driver's
            # rt.init(namespace=...) (absent == default, like every
            # other optional spec field).
            spec["ns_ctx"] = self.namespace
        trace_ctx = _trace_ctx()
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        if scheduling_strategy is not None:
            spec["scheduling_strategy"] = scheduling_strategy
        if pg_context is not None:
            spec["pg_context"] = pg_context
        if runtime_env is not None:
            spec["runtime_env"] = runtime_env
        if mode is not None:
            spec["num_returns_mode"] = mode
        if self._direct is not None and self._direct.eligible(spec):
            fut = self._direct.register(spec)
            fut.hold_refs = [a for a in args if isinstance(a, ObjectRef)]
            self._direct.submit(spec, solo=self._ctx.pending_direct_dep)
        elif self._submit_pipeline is not None:
            self._submit_pipeline.submit(spec)
        else:
            self._client.call("submit_task", spec=spec)
        return [ObjectRef(r, owner=self) for r in returns]

    def create_actor(
        self,
        class_key: str,
        args: Sequence[Any],
        class_name: str,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        concurrency_groups: Optional[Dict[str, int]] = None,
        handle_meta: Optional[dict] = None,
        scheduling_strategy: Optional[dict] = None,
        pg_context: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
        release_creation_resources: bool = False,
    ) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "actor_creation",
            "trace_ctx": _trace_ctx(),
            "name": name,
            # Named-actor registration defaults to the session
            # namespace of the creating process, never a hardcoded one.
            "namespace": namespace or self.namespace,
            "ns_ctx": (
                self.namespace
                if self.namespace != DEFAULT_NAMESPACE
                else None
            ),
            "class_name": class_name,
            "function_key": class_key,
            "args": self._serialize_args(args),
            "returns": [ObjectID.for_return(task_id, 1).binary()],
            # Explicit num_cpus=0 actors request {} — unlimited packing
            # (the reference's many-replica escape hatch); None keeps
            # the 1-CPU scheduling default applied in api_internal.
            "resources": (
                resources if resources is not None else {"CPU": 1.0}
            ),
            # True for default-resource actors: the 1 CPU is a
            # placement-time gate only, returned once the actor is up
            # (reference: DEFAULT_ACTOR_CREATION_CPU_SIMPLE=0 — default
            # actors hold no lifetime CPU).
            "release_creation_resources": release_creation_resources,
            "actor_id": actor_id.binary(),
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "concurrency_groups": concurrency_groups or {},
            "handle_meta": handle_meta,
            "scheduling_strategy": scheduling_strategy,
            "pg_context": pg_context,
            "runtime_env": runtime_env,
        }
        spec = self._prune_spec(spec)
        # One-way: the reply is always {} (creation errors surface
        # through actor state / the creation task's return object),
        # and frames on one connection process in order, so a
        # same-connection method submit can never overtake its
        # create. Pipelining the creates instead of paying one
        # driver->head round trip each is worth ~7ms/actor at the
        # 1000-actor scale.
        self._client.notify("create_actor", spec=spec)
        return actor_id

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method: str,
        args: Sequence[Any],
        num_returns=1,
        max_retries: int = 0,
        concurrency_group: Optional[str] = None,
    ) -> List[ObjectRef]:
        task_id = self._next_task_id()
        mode = num_returns if isinstance(num_returns, str) else None
        n_declared = 1 if mode else num_returns
        returns = [
            ObjectID.for_return(task_id, i + 1) for i in range(n_declared)
        ]
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "kind": "actor_task",
            "trace_ctx": _trace_ctx(),
            "name": method,
            "method": method,
            "function_key": "",
            "args": self._serialize_args(args),
            "returns": [r.binary() for r in returns],
            "resources": {},
            "actor_id": actor_id.binary(),
            "max_retries": max_retries,
            "num_returns_mode": mode,
            "concurrency_group": concurrency_group,
            # No ns_ctx here: actor tasks run under the namespace the
            # actor was CREATED with (its creation spec carried it) —
            # shipping the caller's would be ~100 B/task of dead
            # weight on the hot path.
        }
        spec = self._prune_spec(spec)
        if self._direct is not None:
            fut = self._direct.register(spec)
            fut.hold_refs = [a for a in args if isinstance(a, ObjectRef)]
            self._actor_router(actor_id).submit(spec, fut)
        else:
            self._client.call("submit_actor_task", spec=spec)
        return [ObjectRef(r, owner=self) for r in returns]

    def _actor_router(self, actor_id: ActorID):
        # Locked check-then-create: a lost setdefault race would leak
        # the loser's router thread (started in its __init__), parked
        # forever on an empty queue.
        with self._ref_lock:
            router = self._actor_routers.get(actor_id)
            if router is None:
                from .direct import ActorDirectRouter

                router = self._actor_routers[actor_id] = (
                    ActorDirectRouter(self, actor_id)
                )
            return router

    # ------------------------------------------------------------------
    # misc API
    # ------------------------------------------------------------------
    def call(self, method: str, **kwargs) -> dict:
        return self._client.call(method, **kwargs)

    def notify(self, method: str, **kwargs) -> None:
        self._client.notify(method, **kwargs)

    # ------------------------------------------------------------------
    # worker-role execution loop
    # ------------------------------------------------------------------
    def _on_push(self, channel: str, msg: dict) -> None:
        if channel == "execute_task":
            self._task_queue.put((msg["spec"], None))
        elif channel == "log_lines":
            self._print_worker_logs(msg)
        elif channel == "error_event":
            # Cluster error surfaced even when no get() will raise it
            # (reference: driver prints published error messages).
            print(
                f"[ray_tpu] ({msg.get('source', '?')}) "
                f"{msg.get('message', '')}",
                file=sys.stderr,
            )
        elif channel == "exit":
            self._running = False
            self._task_queue.put(None)

    def _print_worker_logs(self, msg: dict) -> None:
        """Print streamed worker output with source prefixes
        (reference: worker.py:1966 print_to_stdstream with the
        '(pid=…, ip=…)' prefix convention)."""
        node = msg.get("node", "")
        for batch in msg.get("batches", []):
            prefix = f"(worker-{batch['worker']} pid={batch['pid']}" + (
                f" node={node})" if node else ")"
            )
            for line in batch["lines"]:
                print(f"{prefix} {line}", file=sys.stderr)

    def current_pg_context(self) -> Optional[dict]:
        """Capturing-placement-group context of the task this thread is
        executing, if any."""
        return getattr(self._ctx, "pg_context", None)

    def _batch_reclaim_loop(self) -> None:
        """Hand queued-but-unstarted batch specs back to the submitter
        when the running spec won't finish (blocking gang member, long
        compute): the driver re-spreads them across other leases, so
        stacking N specs on this worker can never serialize — or
        deadlock — work the resource model promised to run
        concurrently. Queue.get is atomic, so a spec is either
        reclaimed here or executed by the loop, never both."""
        q = self._task_queue
        evt = self._reclaim_evt
        while self._running:
            # Parked until a batch handler queues specs — the reclaim
            # scan only matters while work is queued BEHIND a running
            # spec, so an idle or sequential-latency worker never pays
            # the 40 Hz poll.
            if q.empty():
                evt.wait(5.0)  # deliberate park with deadline; enqueue sets the event
                evt.clear()
            time.sleep(0.025)
            if q.empty() or not self._inflight_tasks:
                continue  # idle loop drains the queue itself
            try:
                oldest = min(
                    info["started"]
                    for info in list(self._inflight_tasks.values())
                )
            except ValueError:
                continue  # finished between checks
            if time.time() - oldest < 0.05:
                continue
            kept = []
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is not None and type(item[1]) is _BatchSlot:
                    item[1].reply({"requeue": True})
                else:
                    kept.append(item)  # daemon pushes / shutdown None
            for item in kept:
                q.put(item)

    def run_task_loop(self) -> None:
        """Blocking execution loop (reference:
        CoreWorkerProcess::RunTaskExecutionLoop). Consumes both
        daemon-pushed specs (reply_to None) and direct-transport specs
        (reply_to carries the deferred RPC reply handle) from one
        queue, preserving single-threaded execution and per-connection
        arrival order."""
        while self._running:
            item = self._task_queue.get()
            if item is None:
                return
            spec, reply_to = item
            pool = None
            if spec.get("kind") == "actor_task":
                # Concurrent actor: the loop thread only dispatches;
                # up to max_concurrency method calls run on the pool
                # (task context is thread-local, replies are
                # send-locked, so pool threads are safe). Named
                # concurrency groups (reference: concurrency_group_
                # manager.h) each own an independent pool, so a
                # saturated group never stalls another; per-group FIFO
                # order is the pool queue's.
                group = spec.get("concurrency_group")
                if group and self._actor_group_pools:
                    pool = self._actor_group_pools.get(group)
                if pool is None:
                    pool = self._actor_pool
            if pool is not None:
                pool.submit(self._execute, spec, reply_to)
            else:
                self._execute(spec, reply_to)

    def _run_coroutine(self, coro):
        """Execute an async actor method to completion on the actor's
        shared event loop (created on first use). The calling pool
        thread blocks for the result, so max_concurrency bounds
        concurrent coroutines while awaits interleave on the loop."""
        import asyncio

        with self._actor_loop_lock:
            if self._actor_loop is None:
                loop = asyncio.new_event_loop()
                thread = threading.Thread(
                    target=loop.run_forever,
                    name="rt-actor-asyncio",
                    daemon=True,
                )
                thread.start()
                self._actor_loop = loop

        # Thread-local task identity doesn't reach the loop thread, and
        # a thread-local SET there would clobber across interleaved
        # coroutines — carry it in a contextvar, which asyncio keeps
        # task-local (each asyncio.Task copies the context).
        task_id = self._ctx.task_id

        async def _with_task_ctx():
            _ASYNC_TASK_ID.set(task_id)
            return await coro

        return asyncio.run_coroutine_threadsafe(
            _with_task_ctx(), self._actor_loop
        ).result()

    _none_bytes: Optional[bytes] = None

    def _none_wire_bytes(self) -> bytes:
        cached = self._none_bytes
        if cached is None:
            cached = CoreWorker._none_bytes = (
                self.serialization.serialize(None).to_bytes()
            )
        return cached

    def _direct_reply(self, reply_to, payload: dict) -> None:
        if type(reply_to) is tuple:
            conn, mid = reply_to
            conn.reply(mid, payload)
        else:
            reply_to.reply(payload)  # _BatchSlot of an execute_tasks frame

    def _report_direct_task_events(
        self, spec: dict, start: float, failed: bool
    ) -> None:
        """Direct-transport tasks never transit the daemon, so the
        executing worker reports their state events (reference:
        task_event_buffer.h — workers batch events to the GCS). Events
        AND counts accumulate locally and flush as one notify pair
        when the queue drains (rate-limited to 20 Hz) or 0.5 s passes
        — the per-task task_event notify this replaces was its own
        control-plane flood at batched-submit rates."""
        counts = self._direct_task_counts
        events = None
        if self.config.task_events_enabled:
            tid = spec["task_id"]
            base = {
                "task_id": tid.hex() if isinstance(tid, bytes) else str(tid),
                "name": spec.get("name", ""),
                "kind": spec.get("kind", "normal"),
            }
            events = (
                dict(base, state="RUNNING", time=start),
                dict(
                    base,
                    state="FAILED" if failed else "FINISHED",
                    time=time.time(),
                ),
            )
        with counts["lock"]:
            counts["failed" if failed else "finished"] += 1
            if events is not None:
                counts["events"].extend(events)
            now = time.monotonic()
            # Queue-drain flush is UNCONDITIONAL: completion events
            # must reach the daemon before the caller's get() returns
            # (a state/metrics query issued that instant sees the
            # task). Mid-flood the queue is never empty, so events
            # still coalesce into 0.5s/2048-record batches there —
            # the regime the per-task notify was flooding.
            due = (
                now - counts["last_flush"] >= 0.5
                or len(counts["events"]) >= 2048
                or self._task_queue.empty()
            )
            if not due:
                return
            finished, failed_n = counts["finished"], counts["failed"]
            ev_batch = counts["events"]
            counts["finished"] = counts["failed"] = 0
            counts["events"] = []
            counts["last_flush"] = now
        try:
            if ev_batch:
                # One frame carries both events and counts.
                self._client.notify(
                    "task_event", events=ev_batch,
                    finished=finished, failed=failed_n,
                )
            else:
                self._client.notify(
                    "task_counts", finished=finished, failed=failed_n
                )
        except Exception:  # noqa: BLE001 — metrics must not raise
            pass

    def flush_task_events(self) -> None:
        """Force-flush buffered direct-task events/counts (tests and
        state-API consumers that need completion events NOW rather
        than at the next 50ms/queue-drain flush)."""
        counts = self._direct_task_counts
        with counts["lock"]:
            finished, failed_n = counts["finished"], counts["failed"]
            ev_batch = counts["events"]
            counts["finished"] = counts["failed"] = 0
            counts["events"] = []
            counts["last_flush"] = time.monotonic()
        try:
            if ev_batch:
                self._client.notify(
                    "task_event", events=ev_batch,
                    finished=finished, failed=failed_n,
                )
            elif finished or failed_n:
                self._client.notify(
                    "task_counts", finished=finished, failed=failed_n
                )
        except Exception:  # noqa: BLE001 — metrics must not raise
            pass

    def _execute(self, spec: dict, reply_to=None) -> None:
        start_time = time.time()
        task_id = TaskID(spec["task_id"])
        tid_hex = task_id.hex()
        self._inflight_tasks[tid_hex] = {  # rt: noqa[RT201] — per-task dict key: concurrent pool threads touch distinct keys (GIL-atomic setitem)
            "task_id": tid_hex,
            "name": spec.get("name", ""),
            "kind": spec.get("kind", "normal"),
            "started": start_time,
        }
        task_failed = False
        self._ctx.task_id = task_id
        self._ctx.put_index = 0
        self._ctx.submit_index = 0
        self._ctx.task_name = spec.get("name") or spec["kind"]
        # Actor methods inherit the capture context the actor was
        # created with (the creation spec carried it).
        self._ctx.pg_context = spec.get("pg_context") or (
            self._actor_pg_context if spec["kind"] == "actor_task" else None
        )
        # Adopt the submitting driver's session namespace for the span
        # of this task (reference: workers resolve named-actor APIs in
        # the job's ray_namespace). Actors keep the namespace they
        # were CREATED under — it is their identity's namespace — even
        # if a later caller runs in another one.
        if spec["kind"] == "actor_creation":
            self._actor_namespace = spec.get("ns_ctx")  # rt: noqa[RT201] — set once by the creation task, which happens-before any concurrent actor call
        if spec["kind"] in ("actor_creation", "actor_task"):
            self.namespace = self._actor_namespace or DEFAULT_NAMESPACE  # rt: noqa[RT201] — set once by the creation task, which happens-before any concurrent actor call
        else:
            self.namespace = spec.get("ns_ctx") or DEFAULT_NAMESPACE
        if self.job_id._bytes != spec["job_id"]:
            self.job_id = JobID(spec["job_id"])  # rt: noqa[RT201] — set once per task prologue; normal tasks run one at a time on this worker
        trace_stack = None
        try:
            tctx = spec.get("trace_ctx")
            if tctx:
                # Execution span linked under the caller's span
                # (reference: ray's OTel task execution spans).
                import contextlib as _contextlib

                from ..util.tracing import remote_parent
                from ..util.tracing import span as _tspan

                trace_stack = _contextlib.ExitStack()
                trace_stack.enter_context(remote_parent(tctx))
                trace_stack.enter_context(_tspan(
                    "task:" + (spec.get("name") or "anonymous"),
                    kind=spec.get("kind", "normal"),
                ))
            args, kwargs = _split_kwargs(self._deserialize_args(spec["args"]))
            kind = spec["kind"]
            # Actors keep their runtime env for life (they pin this
            # worker); shared task workers restore afterwards. The
            # env-less hot path skips the contextmanager machinery
            # entirely (a reusable nullcontext has no enter state).
            renv = spec.get("runtime_env")
            if renv:
                from .runtime_env import apply_runtime_env

                env_ctx = apply_runtime_env(
                    renv, self, restore=(kind != "actor_creation")
                )
            else:
                env_ctx = _NULL_CTX
            with env_ctx:
                if kind == "actor_creation":
                    cls = self.functions.fetch(spec["function_key"])
                    self._actor_instance = cls(*args, **kwargs)  # rt: noqa[RT201] — creation task publishes the instance before the daemon routes any calls to it
                    self._actor_id = ActorID(spec["actor_id"])  # rt: noqa[RT201] — creation task publishes before any concurrent actor call exists
                    self._actor_pg_context = spec.get("pg_context")  # rt: noqa[RT201] — creation task publishes before any concurrent actor call exists
                    concurrency = int(spec.get("max_concurrency") or 1)
                    groups = spec.get("concurrency_groups") or {}
                    if concurrency > 1 or groups:
                        # Concurrent actor (reference: concurrency_
                        # group_manager.h / threaded+async actors):
                        # method calls dispatch to a pool of N threads;
                        # coroutine-returning methods additionally run
                        # on a shared event loop so they can await each
                        # other while the pool bounds concurrency.
                        # With named groups, the DEFAULT pool exists
                        # even at width 1: default-group calls must
                        # not run inline on the dispatch thread, or a
                        # blocked default method would stall dispatch
                        # into every other group.
                        import concurrent.futures

                        self._actor_pool = (  # rt: noqa[RT201] — pool built during creation, before the concurrency it provides exists
                            concurrent.futures.ThreadPoolExecutor(
                                max_workers=concurrency,
                                thread_name_prefix="rt-actor-exec",
                            )
                        )
                        self._actor_group_pools = {  # rt: noqa[RT201] — group pools built during creation, before the concurrency they provide exists
                            gname: concurrent.futures.ThreadPoolExecutor(
                                max_workers=int(width),
                                thread_name_prefix=f"rt-actor-{gname}",
                            )
                            for gname, width in groups.items()
                        }
                    results = [None]
                elif kind == "actor_task":
                    if self._actor_instance is None:
                        raise exc.ActorDiedError("actor instance missing")
                    if spec["method"] == "__rt_dag_loop__":
                        # Compiled-DAG execution loop: the actor blocks
                        # on its channels until torn down
                        # (dag/compiled.py).
                        from ..dag.compiled import dag_exec_loop

                        value = dag_exec_loop(
                            self._actor_instance, *args, **kwargs
                        )
                    else:
                        method = getattr(
                            self._actor_instance, spec["method"]
                        )
                        value = method(*args, **kwargs)
                        if inspect.iscoroutine(value):
                            value = self._run_coroutine(value)
                    results = self._collect_returns(task_id, spec, value)
                else:
                    func = self.functions.fetch(spec["function_key"])
                    value = func(*args, **kwargs)
                    results = self._collect_returns(task_id, spec, value)
        except BaseException as e:  # noqa: BLE001 — any task failure
            if trace_stack is not None:
                # The stack closes exception-free in `finally` (the
                # error was caught here), so the execution span must be
                # marked failed explicitly.
                from ..util.tracing import add_span_attributes

                add_span_attributes(error=repr(e))
            task_failed = True
            payload = make_exception_payload(e)
            if reply_to is not None:
                # Events before the reply: a state/timeline query
                # issued the moment get() unblocks should see the task.
                self._report_direct_task_events(spec, start_time, True)
                self._direct_reply(reply_to, {"error": payload})
            else:
                self._client.notify(
                    "task_done",
                    task_id=spec["task_id"],
                    error=payload,
                    system_error=False,
                )
            return
        finally:
            if trace_stack is not None:
                trace_stack.close()
            self._inflight_tasks.pop(tid_hex, None)
            rec = _flight()
            if rec.enabled:
                rec.record(
                    "task",
                    spec.get("name") or spec["kind"],
                    (time.time() - start_time) * 1e3,
                    {"task_kind": spec["kind"], "error": True}
                    if task_failed
                    else {"task_kind": spec["kind"]},
                )
            self._ctx.task_id = None
            self._ctx.pg_context = None
            self._ctx.task_name = ""
        if reply_to is not None:
            # Direct transport: results ride the reply — small ones
            # inline (never touching the daemon), large ones sealed
            # into the shared store and reported so any process can
            # map them zero-copy.
            try:
                wire = []
                for oid_bytes, value in zip(spec["returns"], results):
                    if value is None:
                        # The nop/side-effect-task result: one cached
                        # wire blob instead of a fresh cloudpickle per
                        # task at batched-execute rates.
                        wire.append(("inline", self._none_wire_bytes()))
                        continue
                    serialized = self.serialization.serialize(value)
                    size = serialized.total_size()
                    if size <= self.config.max_direct_call_object_size:
                        wire.append(("inline", serialized.to_bytes()))
                    else:
                        oid = ObjectID(oid_bytes)
                        buf = self._store_create(oid, size)
                        used = serialized.write_to(buf)
                        self._seal_and_report(oid, used)
                        wire.append(("shm", used))
            except BaseException as e:  # noqa: BLE001
                self._report_direct_task_events(spec, start_time, True)
                self._direct_reply(reply_to, {"error": make_error_payload(
                    "TaskError", f"failed to store results: {e!r}"
                )})
                return
            self._report_direct_task_events(spec, start_time, False)
            self._direct_reply(reply_to, {"results": wire})
            return
        try:
            for oid_bytes, value in zip(spec["returns"], results):
                self.put_object(ObjectID(oid_bytes), value)
        except BaseException as e:  # noqa: BLE001
            self._client.notify(
                "task_done",
                task_id=spec["task_id"],
                error=make_error_payload(
                    "TaskError", f"failed to store results: {e!r}"
                ),
                system_error=False,
            )
            return
        self._client.notify("task_done", task_id=spec["task_id"], error=None)

    def _deserialize_args(self, wire_args: List[tuple]) -> List[Any]:
        args = []
        ref_slots: List[int] = []
        ref_blobs: List[bytes] = []
        deserialize = self.serialization.deserialize
        for kind, payload in wire_args:
            if kind == "inline":
                args.append(deserialize(payload))
            else:
                ref_slots.append(len(args))
                ref_blobs.append(payload)
                args.append(None)
        if not ref_slots:
            return args
        if len(ref_slots) == 1:
            args[ref_slots[0]] = self._get_one(
                ObjectID(ref_blobs[0]), timeout=None
            )
            return args
        for slot, value in zip(ref_slots, self._get_many(ref_blobs)):
            args[slot] = value
        return args

    def _get_many(self, oid_blobs: List[bytes]) -> List[Any]:
        """Resolve many refs with ONE `get_objects` round trip for
        everything the daemon already holds (the many-arg task path:
        per-arg blocking gets made one 10k-arg task cost 10k RTTs).
        Unready/remote entries fall back to the blocking per-oid get,
        which pulls and waits exactly like before."""
        # The RPC is deduped per unique oid, but DESERIALIZATION runs
        # once per arg position: duplicate ref args must stay
        # independent objects (a task mutating args[0] in place must
        # not see the change through args[1] — the per-arg blocking
        # path always gave fresh deserializations).
        inline_payloads: Dict[bytes, Any] = {}
        shm_sizes: Dict[bytes, int] = {}
        via_src: Dict[bytes, tuple] = {}
        unique = list(dict.fromkeys(oid_blobs))
        remote: List[bytes] = []
        for blob in unique:
            oid = ObjectID(blob)
            with self._ref_lock:
                cached = self._inline_cache.get(oid)
            if cached is not None:
                inline_payloads[blob] = cached
            else:
                remote.append(blob)
        if remote:
            try:
                reply = self._client.call(
                    "get_objects", oids=remote, timeout=120.0
                )
                results = reply.get("results") or []
            except RpcError:
                results = []
            for blob, res in zip(remote, results):
                if res.get("error") is not None:
                    raise_from_payload(res["error"])
                if res.get("inline") is not None:
                    inline_payloads[blob] = res["inline"]
                elif res.get("shm_size") is not None:
                    shm_sizes[blob] = res["shm_size"]
                    if res.get("via"):
                        via_src[blob] = (
                            res["via"], str(res.get("src", ""))
                        )
                # pending: blocking fallback below
        out = []
        for blob in oid_blobs:
            if blob in inline_payloads:
                payload = inline_payloads[blob]
                self._record_get("inline", "", len(payload), 0.0)
                out.append(self.serialization.deserialize(payload))
            elif blob in shm_sizes:
                t0 = time.monotonic()
                try:
                    value = self._read_local_store(
                        ObjectID(blob), shm_sizes[blob], 30.0
                    )
                except (FileNotFoundError, exc.GetTimeoutError):
                    # evicted mid-fetch: blocking path re-pulls
                    out.append(
                        self._get_one(ObjectID(blob), timeout=None)
                    )
                    continue
                via, src = via_src.get(blob, (None, ""))
                self._record_get(
                    self._VIA_PROVENANCE.get(via, "local"), src,
                    shm_sizes[blob],
                    (time.monotonic() - t0) * 1e3,
                )
                out.append(value)
            else:
                out.append(self._get_one(ObjectID(blob), timeout=None))
        return out

    def _collect_returns(
        self, task_id: TaskID, spec: dict, value: Any
    ) -> List[Any]:
        """Normal returns are split across the declared return ids;
        generator tasks ("dynamic"/"streaming") seal each yielded item
        under its deterministic id as produced, then return the
        completion marker (an ObjectRefGenerator carrying the count)
        as the single declared return (reference:
        python/ray/_raylet.pyx streaming generator protocol)."""
        mode = spec.get("num_returns_mode")
        if not mode:
            return self._split_returns(value, len(spec["returns"]))
        if not hasattr(value, "__iter__") and not hasattr(
            value, "__next__"
        ):
            raise TypeError(
                f"num_returns={mode!r} requires the task to return a "
                f"generator or iterable, got {type(value).__name__}"
            )
        from ..object_ref import ObjectRefGenerator

        count = 0
        try:
            for item in value:
                self.put_object(
                    ObjectID.for_return(task_id, count + 2), item
                )
                count += 1
        except BaseException as e:
            # Consumers must still receive the items sealed before the
            # failure; the error payload carries the emitted count.
            e.__rt_items_emitted__ = count
            raise
        return [ObjectRefGenerator(task_id, count=count)]

    @staticmethod
    def _split_returns(value: Any, num_returns: int) -> List[Any]:  # noqa: D102
        if num_returns == 1:
            return [value]
        if not isinstance(value, (tuple, list)) or len(value) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{type(value).__name__}"
            )
        return list(value)

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self.flush_pending_dels()
        if self._submit_pipeline is not None:
            # Queued batch submissions must reach the daemon before
            # the connection dies (their returns are already handed
            # out as ObjectRefs).
            self._submit_pipeline.flush(5.0)
            self._submit_pipeline.shutdown()
        self._running = False
        self._del_flush_evt.set()  # unpark the flusher so it exits
        if self._direct is not None:
            self._direct.shutdown()
        for router in list(self._actor_routers.values()):
            router.shutdown()
        self._actor_routers.clear()
        if self._direct_server is not None:
            try:
                self._direct_server.close()
            except Exception:
                pass
        try:
            self._client.close()
        except Exception:
            pass
        self.store.shutdown(unlink=False)
