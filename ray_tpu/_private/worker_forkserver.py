"""Warm fork-server for worker processes.

A cold worker spawn pays ~250ms of interpreter + import time
(`ray_tpu` -> rpc/wire/protobuf/numpy), which caps actor-creation
throughput at a handful per second per core — far below the
many-dedicated-worker pattern the reference's worker pool serves
(reference: src/ray/raylet/worker_pool.cc starts one process per
actor, bounded only by maximum_startup_concurrency). This template
process imports the worker's full module graph ONCE, then forks a
child per spawn request: each fork costs ~10ms and shares the warm
interpreter's pages copy-on-write.

Protocol (newline-delimited JSON over the stdin/stdout pipe pair):
  request:  {"log": "<path>", "env": {"K": "v" | null, ...}}
  reply:    {"pid": N} | {"error": "..."}

`env` values of null unset the variable in the child. The template
itself must never touch accelerators or open RPC connections — forked
children would share them; it only imports modules. Children are
reaped here (they are this process's children, not the daemon's); the
daemon tracks liveness by pid signal-0 probes.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback


def _reaper() -> None:
    """Reap exited children so they never linger as zombies (the
    daemon cannot waitpid them — they are not its children)."""
    while True:
        try:
            pid, _status = os.waitpid(-1, 0)
            if pid == 0:
                time.sleep(0.2)
        except ChildProcessError:
            time.sleep(0.5)
        except InterruptedError:
            continue


def _run_child(log_path: str, env: dict) -> None:
    """Child-side setup after fork: detach from the request pipe,
    point stdout/stderr at the worker log, apply the env deltas, and
    run the normal worker entrypoint."""
    try:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        if fd > 2:
            os.close(fd)
        os.close(0)
        for key, value in env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = str(value)
        from .worker_main import main as worker_main

        worker_main()
    except BaseException:
        traceback.print_exc()
    finally:
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        # Skip interpreter finalization: the child inherited the
        # template's atexit/threading state, which was never meant to
        # shut down a worker.
        os._exit(0)


def _proc_starttime(pid: int):
    """Kernel start time (clock ticks since boot) of `pid`, or None if
    the process is gone. Field 22 of /proc/<pid>/stat; parse after the
    last ')' — the comm field may itself contain spaces or parens."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


class ForkedProc:
    """Popen-shaped handle for a fork-server child. The child belongs
    to the fork-server process (which reaps it immediately), so
    waitpid is unavailable here AND a bare signal-0 probe is unsafe:
    the reaped pid can be recycled by an unrelated process, making a
    dead worker look alive (and leaking its startup-concurrency slot
    for the whole watch window). Liveness = pid exists AND its
    /proc starttime matches the one captured at fork."""

    def __init__(self, pid: int, starttime=None):
        self.pid = pid
        self._returncode = None
        # The template reports the starttime it read while the child
        # was still its un-reaped child (zombie at worst) — the only
        # point where the pid provably can't have been recycled. None
        # means the template's reaper won the race: the child is
        # already dead, and poll() reports it so without ever
        # trusting the (possibly recycled) pid.
        self._starttime = starttime

    def poll(self):
        if self._returncode is not None:
            return self._returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self._returncode = 0
            return 0
        except PermissionError:
            # pid reused by another user's process: ours is gone.
            self._returncode = 0
            return 0
        now = _proc_starttime(self.pid)
        if self._starttime is None or now != self._starttime:
            # Same pid, different (or vanished) start time: the pid
            # was recycled after our child exited.
            self._returncode = 0
            return 0
        return None

    def terminate(self) -> None:
        if self.poll() is not None:  # dead/recycled: never signal it
            return
        try:
            os.kill(self.pid, 15)
        except (ProcessLookupError, PermissionError):
            pass

    def kill(self) -> None:
        if self.poll() is not None:
            return
        try:
            os.kill(self.pid, 9)
        except (ProcessLookupError, PermissionError):
            pass

    def wait(self, timeout=None):
        import subprocess

        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self._returncode


class ForkServerClient:
    """Daemon-side handle on one fork-server template process.

    `spawn` is serialized under a lock (the pipe is a single
    request/reply stream); a dead or wedged template is restarted
    once, and a second failure surfaces as None so the caller can fall
    back to a cold subprocess spawn."""

    #: Seconds to wait for the template's import phase / a fork reply.
    READY_TIMEOUT = 30.0

    def __init__(self, base_env: dict, log_path: str):
        self._base_env = base_env
        self._log_path = log_path
        self._lock = threading.Lock()
        self._proc = None
        self._ready = False
        self._buf = b""
        # Latched after a restart-and-retry cycle also fails: the
        # environment can't run the template, so stop paying the
        # launch + timeout cost on every spawn and let callers use
        # the cold path permanently.
        self._dead = False

    def start(self) -> None:
        """Launch the template (non-blocking; the first spawn waits
        for its ready line)."""
        with self._lock:
            self._ensure_started()

    def _ensure_started(self) -> None:
        import subprocess

        if self._proc is not None and self._proc.poll() is None:
            return
        log_file = open(self._log_path, "ab")
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m",
                 "ray_tpu._private.worker_forkserver"],
                env=self._base_env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=log_file,
            )
        finally:
            log_file.close()
        self._ready = False
        self._buf = b""

    def _read_reply(self, timeout: float):
        """One JSON line from the template, bounded by `timeout` even
        mid-line (a wedged template that wrote a partial line must not
        block the caller — it holds the daemon's dispatch lock)."""
        import select

        fd = self._proc.stdout.fileno()
        deadline = time.monotonic() + timeout
        while b"\n" not in self._buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ready, _, _ = select.select([fd], [], [], remaining)  # rt: noqa[RT203] — _lock serializes the whole request/reply conversation; this select IS the reply wait
            if not ready:
                return None
            chunk = os.read(fd, 65536)
            if not chunk:  # template EOF (crashed)
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            return None

    def spawn(self, log: str, env: dict):
        """Fork one worker; returns a ForkedProc or None on failure."""
        with self._lock:
            if self._dead:
                return None
            for _attempt in (0, 1):
                try:
                    self._ensure_started()
                    if not self._ready:
                        hello = self._read_reply(self.READY_TIMEOUT)
                        if not (hello and hello.get("ready")):
                            raise OSError("fork server never came up")
                        self._ready = True
                    req = json.dumps({"log": log, "env": env}) + "\n"
                    self._proc.stdin.write(req.encode())
                    self._proc.stdin.flush()
                    reply = self._read_reply(self.READY_TIMEOUT)
                    if reply and "pid" in reply:
                        return ForkedProc(
                            reply["pid"], reply.get("starttime")
                        )
                except (OSError, ValueError, BrokenPipeError):
                    pass
                # Template died mid-request: restart once and retry.
                self._kill_locked()
            self._dead = True
            return None

    def _kill_locked(self) -> None:
        if self._proc is not None:
            try:
                self._proc.kill()
                self._proc.wait(timeout=2)
            except Exception:
                pass
            self._proc = None
            self._ready = False

    def close(self) -> None:
        with self._lock:
            self._kill_locked()


def main() -> None:
    # Pre-import the worker's entire module graph; every fork inherits
    # the warm interpreter. worker_main pulls ray_tpu -> worker ->
    # rpc/wire (protobuf) -> object_store (numpy) -> serialization.
    from . import worker_main  # noqa: F401

    # Modules the worker pulls LAZILY (first CoreWorker init / first
    # task) import here instead — measured at ~0.25s of post-fork CPU
    # per child without this (runtime_env -> zipfile/pathlib, plus the
    # native store's ctypes dlopen), which dominated actor-creation
    # throughput on small hosts. dlopen'd libraries and compiled
    # bytecode are inherited copy-on-write; loading the .so here is
    # safe (no store ATTACH — fds stay per-child).
    from . import runtime_env  # noqa: F401
    from . import accelerators  # noqa: F401

    # The RPC hub/pool layers lazily `from concurrent.futures import
    # ThreadPoolExecutor` (and the hub imports selectors) on first
    # use — post-fork in every child. This image ships NO bytecode
    # cache for them and sets PYTHONDONTWRITEBYTECODE=1, so each of N
    # workers recompiled the package from source (~30ms of pure CPU a
    # worker, the dominant cost of an actor-creation storm). Compile
    # once here; children inherit the warm modules.
    # NB: `import concurrent.futures` alone does NOT load the
    # `.thread` submodule (lazy __getattr__ in 3.12) — name the
    # class so the submodule actually compiles here.
    from concurrent.futures import ThreadPoolExecutor  # noqa: F401
    import selectors  # noqa: F401
    import http.client  # noqa: F401 — serve replicas' first import

    try:
        from .._native import load_library

        load_library()
    except Exception:
        pass  # native store disabled/unbuilt: children fall back too

    threading.Thread(target=_reaper, daemon=True).start()
    out_fd = sys.stdout.fileno()
    # Signal readiness so the daemon can distinguish "template still
    # importing" from "template wedged".
    os.write(out_fd, b'{"ready": true}\n')
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            pid = os.fork()
        except Exception as e:  # bad request or fork failure
            os.write(
                out_fd,
                (json.dumps({"error": repr(e)}) + "\n").encode(),
            )
            continue
        if pid == 0:
            _run_child(req["log"], req.get("env") or {})
            # unreachable: _run_child always os._exit()s
        # Capture the child's authoritative start time HERE, where the
        # pid cannot have been recycled yet: until the reaper thread
        # waitpid()s it, the child (even exited) holds its /proc entry
        # as our zombie. If the reaper won the race the read fails and
        # the daemon treats the handle as dead-at-creation — safe, and
        # never an impostor's starttime.
        os.write(
            out_fd,
            (
                json.dumps(
                    {"pid": pid, "starttime": _proc_starttime(pid)}
                )
                + "\n"
            ).encode(),
        )


if __name__ == "__main__":
    main()
