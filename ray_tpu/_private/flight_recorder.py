"""Always-on per-process flight recorder.

Reference: the per-component event rings the reference keeps hot and
dumps cold — task events batched in core_worker/task_event_buffer.h,
the asio handler stats of common/event_stats.cc, and the debug-state
dumps raylets write on demand. The rebuild's version is ONE ring per
process (daemon, worker, driver alike) recording the events that
matter when a gang step stalls:

  rpc.client   — request/response latency of every outbound call
                 (method, ms, error) — hooked in rpc.RpcClient
  rpc.server   — handler execution + dispatch-queue wait per inbound
                 request — hooked in rpc.RpcServer._dispatch
  task         — task begin/end with duration and failure flag —
                 hooked in worker.CoreWorker._execute
  store.put /  — object-store writes/reads with payload size and
  store.get      duration — hooked in the worker's object plane
  lock.wait    — time spent waiting on a daemon hot-path lock

Steady-state cost is one `time` read plus a deque append (~1 us);
rings are NEVER pushed — the head pulls them lazily over the
`flight_recorder` RPC when an operator (or `ray_tpu doctor`) asks.
Disable with ``RT_flight_recorder_enabled=0`` (config flag
`flight_recorder_enabled`); disabled cost is one attribute read per
hook site.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "recorder",
    "configure",
    "record",
    "snapshot",
]


class FlightRecorder:
    """Bounded in-memory event ring.

    Records are tuples ``(t, kind, name, dur_ms, extra)`` — `extra` is
    None on the hot path unless a hook passes keyword fields. Appends
    are lock-free (deque.append is GIL-atomic); `snapshot` copies under
    a lock only to get a consistent list view.
    """

    __slots__ = ("enabled", "_ring", "_lock", "_dropped")

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.enabled = enabled
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._lock = threading.Lock()
        self._dropped = 0

    def record(
        self,
        kind: str,
        name: str,
        dur_ms: float,
        extra: Optional[dict] = None,
    ) -> None:
        if not self.enabled:
            return
        self._ring.append((time.time(), kind, name, dur_ms, extra))

    def snapshot(
        self,
        limit: int = 0,
        kinds: Optional[List[str]] = None,
    ) -> List[dict]:
        """Newest-last list of record dicts (wire-friendly)."""
        with self._lock:
            records = list(self._ring)
        if kinds:
            wanted = set(kinds)
            records = [r for r in records if r[1] in wanted]
        if limit and limit > 0:
            records = records[-int(limit):]
        out = []
        for t, kind, name, dur_ms, extra in records:
            # Base fields win on collision: a hook's extra payload
            # must never rewrite what/when the ring recorded.
            rec = dict(extra) if extra else {}
            rec.update(
                t=t,
                kind=kind,
                name=name,
                dur_ms=round(float(dur_ms), 3),
            )
            out.append(rec)
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def resize(self, capacity: int) -> None:
        with self._lock:
            old = self._ring
            snap = list(old)
            self._ring = deque(snap, maxlen=max(16, int(capacity)))
            # record() is deliberately lock-free, so an append can
            # race this swap into the retired deque — fold those
            # stragglers in rather than losing them. (An append that
            # grabbed `old` and lands after the line below is still
            # lost; for a diagnostic ring that sliver beats locking
            # the hot path.)
            for item in list(old)[len(snap):]:
                self._ring.append(item)

    def summary(self) -> Dict[str, dict]:
        """Per-(kind, name) aggregate of the current ring: count, mean
        and max duration, error count — the digest `ray_tpu doctor`
        folds into its verdict."""
        with self._lock:
            records = list(self._ring)
        agg: Dict[str, dict] = {}
        for _, kind, name, dur_ms, extra in records:
            key = f"{kind}:{name}"
            row = agg.get(key)
            if row is None:
                row = agg[key] = {
                    "kind": kind,
                    "name": name,
                    "count": 0,
                    "total_ms": 0.0,
                    "max_ms": 0.0,
                    "errors": 0,
                }
            row["count"] += 1
            row["total_ms"] += float(dur_ms)
            if dur_ms > row["max_ms"]:
                row["max_ms"] = float(dur_ms)
            if extra and extra.get("error"):
                row["errors"] += 1
        for row in agg.values():
            row["mean_ms"] = round(row["total_ms"] / row["count"], 3)
            row["total_ms"] = round(row["total_ms"], 1)
            row["max_ms"] = round(row["max_ms"], 3)
        return agg


def _env_enabled() -> bool:
    raw = os.environ.get("RT_flight_recorder_enabled", "1")
    return raw.lower() in ("1", "true", "yes")


_GLOBAL = FlightRecorder(
    capacity=int(os.environ.get("RT_flight_recorder_capacity", "4096")),
    enabled=_env_enabled(),
)


def recorder() -> FlightRecorder:
    return _GLOBAL


def configure(config) -> None:
    """Apply a resolved runtime Config (daemons at construction,
    workers/drivers after registration hands them the cluster
    config). An explicit RT_flight_recorder_enabled in THIS process's
    environment wins over the cluster config — it is the documented
    per-process kill-switch, and the cluster config (resolved where
    the cluster was created) knows nothing about this process's
    env."""
    if "RT_flight_recorder_enabled" in os.environ:
        _GLOBAL.enabled = _env_enabled()
    else:
        _GLOBAL.enabled = bool(
            getattr(config, "flight_recorder_enabled", True)
        )
    if "RT_flight_recorder_capacity" in os.environ:
        capacity = int(os.environ["RT_flight_recorder_capacity"])
    else:
        capacity = int(
            getattr(config, "flight_recorder_capacity", 4096) or 4096
        )
    if capacity != _GLOBAL._ring.maxlen:
        _GLOBAL.resize(capacity)


def record(
    kind: str, name: str, dur_ms: float, extra: Optional[dict] = None
) -> None:
    _GLOBAL.record(kind, name, dur_ms, extra)


def snapshot(limit: int = 0, kinds=None) -> List[dict]:
    return _GLOBAL.snapshot(limit=limit, kinds=kinds)


def _reset_after_fork() -> None:
    # Forked children share the parent's ring OBJECT; give them a
    # fresh one so a worker's records never interleave with the
    # template process's.
    global _GLOBAL
    _GLOBAL = FlightRecorder(
        capacity=_GLOBAL._ring.maxlen or 4096, enabled=_GLOBAL.enabled
    )


os.register_at_fork(after_in_child=_reset_after_fork)
