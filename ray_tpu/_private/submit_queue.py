"""Driver-side submit pipeline for the daemon task path.

Coalesces task specs into `submit_tasks` batch RPCs so one wire round
trip covers N submissions (reference: the CoreWorker submit path pays
one raylet round trip per task; ROADMAP item 3 measured that cost at
3-4x vs actor calls). Engaged transparently by `worker.submit_task`
for specs the direct transport cannot take (scheduling strategies,
TPU gangs, runtime envs, `use_direct_calls=False`); `.remote()`
callers change nothing.

Semantics:

* Specs flush in submission order on one connection; the daemon's
  per-connection ordered drain preserves batch order, so per-driver
  submission order is preserved.
* A batch is an envelope, not a semantic unit: per-spec decode
  failures come back as {index: error} and seal only that spec's
  returns; the other specs in the batch proceed.
* Transport failures retry the WHOLE batch (bounded, with backoff);
  head-side ingestion dedups by task_id, so a batch whose first
  attempt half-landed re-ingests only the missing specs —
  exactly-once.
* A bounded in-flight window (config submit_inflight_batches) is the
  backpressure: beyond it specs queue driver-side, absorbing floods
  without flooding the wire.

Kill switch: config task_submit_batching=False keeps the old blocking
per-task `submit_task` RPC (`worker.submit_task` never constructs this
pipeline then).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from .task_spec import make_error_payload
from .wire import encode_spec, encode_spec_batch

#: Transport-level retries per batch before failing its specs.
_BATCH_RETRIES = 3


class _Entry:
    __slots__ = ("blob", "returns")

    def __init__(self, blob: bytes, returns: list):
        self.blob = blob
        self.returns = returns


class SubmitPipeline:
    """Batched, pipelined `submit_tasks` sender (one per driver)."""

    def __init__(self, core):
        self._core = core
        cfg = core.config
        self._batch_max = max(1, cfg.submit_batch_max_specs)
        self._window = max(1, cfg.submit_inflight_batches)
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._inflight = 0  # batches currently on the wire
        self._idle = threading.Event()  # set when queue+inflight empty
        self._idle.set()
        self._closed = False

    # -- submission ----------------------------------------------------
    def submit(self, spec: dict) -> None:
        entry = _Entry(encode_spec(spec), spec["returns"])
        batch = None
        with self._lock:
            self._queue.append(entry)
            self._idle.clear()
            if self._inflight < self._window:
                self._inflight += 1
                batch = self._take_locked()
        if batch:
            self._send(batch, _BATCH_RETRIES)

    def _take_locked(self) -> List[_Entry]:
        n = min(self._batch_max, len(self._queue))
        pop = self._queue.popleft
        return [pop() for _ in range(n)]

    def _send(self, batch: List[_Entry], retries_left: int) -> None:
        self._core._client.call_async(
            "submit_tasks",
            lambda reply: self._on_reply(batch, retries_left, reply),
            specs=encode_spec_batch(e.blob for e in batch),
            count=len(batch),
        )

    # -- replies -------------------------------------------------------
    def _on_reply(self, batch, retries_left: int, reply: dict) -> None:
        err = reply.get("_error")
        if err is not None and retries_left > 0 and err in (
            "__chaos_injected_failure__",
            "__connection_lost__",
        ):
            # Whole-batch transport retry: head ingestion dedups by
            # task_id, so re-sending a half-landed batch is
            # exactly-once. Backoff rides a timer thread — reply
            # callbacks must not sleep on the RPC work pool.
            if err == "__connection_lost__":
                try:
                    self._core._client._reconnect()
                except Exception:
                    pass
            timer = threading.Timer(
                0.05 * (_BATCH_RETRIES - retries_left + 1),
                self._send,
                args=(batch, retries_left - 1),
            )
            timer.daemon = True  # never block interpreter exit
            timer.start()
            return
        if err is not None:
            # Out of retries (or a handler error): fail each spec's
            # returns individually — error semantics stay per-spec.
            payload = make_error_payload(
                "TaskError", f"batch submission failed: {err}"
            )
            for entry in batch:
                self._seal_errors(entry, payload)
        else:
            for index, detail in (reply.get("errors") or {}).items():
                # Per-spec ingest failure (malformed blob): only this
                # spec's returns fail.
                self._seal_errors(
                    batch[int(index)],
                    make_error_payload(
                        "TaskError", f"spec rejected by head: {detail}"
                    ),
                )
        next_batch = None
        with self._lock:
            if self._queue and not self._closed:
                next_batch = self._take_locked()
            else:
                self._inflight -= 1
                if self._inflight == 0 and not self._queue:
                    self._idle.set()
        if next_batch:
            self._send(next_batch, _BATCH_RETRIES)

    def _seal_errors(self, entry: _Entry, payload: bytes) -> None:
        for ret in entry.returns:
            try:
                self._core._client.call(
                    "seal_error", oid=ret, error=payload, timeout=10.0
                )
            except Exception:
                # The connection is gone (the usual reason a batch
                # exhausted its retries): every further seal would eat
                # its own 10s timeout — up to 1024 of them for a full
                # window — so stop after the first failure. Nothing
                # daemon-side can answer these returns anyway.
                return

    # -- lifecycle -----------------------------------------------------
    def flush(self, timeout: Optional[float] = 30.0) -> bool:
        """Block until every queued spec has been accepted by the
        daemon (or failed). Returns False on timeout."""
        return self._idle.wait(timeout)

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
