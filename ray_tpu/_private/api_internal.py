"""Glue between the public decorators and the CoreWorker.

Option resolution mirrors the reference's option table
(reference: python/ray/_private/ray_option_utils.py): `num_cpus`,
`num_tpus` (the accelerator analog of num_gpus), `resources={...}`,
`num_returns`, `max_retries`, actor `name`/`namespace`/`max_restarts`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from .. import exceptions as exc
from ..actor import ActorClass, ActorHandle
from ..remote_function import RemoteFunction
from .runtime_env import prepare_runtime_env
from .worker import CoreWorker, global_worker


def _require_worker() -> CoreWorker:
    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError(
            "ray_tpu.init() must be called before using the API"
        )
    return worker


def _flatten_args(args: tuple, kwargs: dict) -> Sequence[Any]:
    # Kwargs ride as a trailing marker tuple; the executor re-splits.
    if not kwargs:
        return list(args)
    return list(args) + [("__kwargs__", kwargs)]


#: Interned resource dicts: nearly every task in a big submission
#: shares one of a handful of shapes ({"CPU": 1.0}, ...), and a fresh
#: dict per task measured ~165 B/task of the driver's 1M-queue RSS.
#: Shared dicts are safe because NOTHING mutates a spec's resources
#: in the submitting process (rewrite_request copies; the daemon
#: works on its own unpickled copy). Bounded so adversarial unique
#: shapes can't grow it without limit.
_RESOURCE_INTERN: Dict[tuple, dict] = {}


def _task_resources(options: Dict[str, Any], default_cpu: float) -> dict:
    num_cpus = options.get("num_cpus")
    num_tpus = options.get("num_tpus")
    if (
        not options.get("resources")
        and num_cpus is None
        and not num_tpus
    ):
        # Fast path for the overwhelmingly common default shape: no
        # per-task dict build, no sort key (the submit hot path runs
        # this once per task at 15k+/s).
        key = ("default", default_cpu)
        cached = _RESOURCE_INTERN.get(key)
        if cached is None:
            cached = {"CPU": float(default_cpu)} if default_cpu else {}
            _RESOURCE_INTERN[key] = cached
        return cached
    resources = dict(options.get("resources") or {})
    resources["CPU"] = float(default_cpu if num_cpus is None else num_cpus)
    if num_tpus:
        resources["TPU"] = float(num_tpus)
    out = {k: v for k, v in resources.items() if v}
    key = tuple(sorted(out.items()))
    cached = _RESOURCE_INTERN.get(key)
    if cached is not None:
        return cached
    if len(_RESOURCE_INTERN) < 1024:
        _RESOURCE_INTERN[key] = out
    return out


def _export_cached(obj, cache_holder, attr: str, worker) -> str:
    """Export once per session: the cache is invalidated when the
    worker changes (shutdown()+init() starts a fresh KV). Keyed on the
    worker's generation token so a module-level @remote function doesn't
    pin a dead worker (and its RPC client) alive after shutdown()."""
    cached = getattr(cache_holder, attr)
    if cached is not None and cached[0] == worker.generation:
        return cached[1]
    key = worker.functions.export(obj)
    setattr(cache_holder, attr, (worker.generation, key))
    return key


_strategy_to_spec = None


def _strategy(options: Dict[str, Any]):
    global _strategy_to_spec
    if _strategy_to_spec is None:  # one-time import, off the hot path
        from ..util.scheduling_strategies import strategy_to_spec

        _strategy_to_spec = strategy_to_spec
    return _strategy_to_spec(options.get("scheduling_strategy"))


def _resolve_placement(
    options: Dict[str, Any], resources: dict, worker: CoreWorker
):
    """Rewrite a placement-group-targeted request onto the group's
    formatted resources (reference: BundleSpecification formatted
    resources; the scheduler then needs no PG special-casing).

    A task running inside a capturing group submits children that
    inherit the group (wildcard bundle) unless they name their own
    strategy (reference: placement_group_capture_child_tasks,
    actor.py:890). Returns (resources, strategy_spec, pg_context).
    """
    from .placement_groups import rewrite_request

    spec = _strategy(options)
    if not spec and options.get("scheduling_strategy") is None:
        inherited = worker.current_pg_context()
        if inherited is not None:
            rewritten = rewrite_request(resources, inherited["pg_id"], -1)
            return rewritten, {"type": "DEFAULT"}, inherited
    if not spec or spec.get("type") != "PLACEMENT_GROUP":
        return resources, spec, None
    rewritten = rewrite_request(
        resources, spec["pg_id"], spec.get("bundle_index", -1)
    )
    pg_context = (
        {"pg_id": spec["pg_id"]} if spec.get("capture") else None
    )
    return rewritten, {"type": "DEFAULT"}, pg_context


def submit_function(rf: RemoteFunction, args: tuple, kwargs: dict):
    worker = _require_worker()
    plan = rf._submit_plan
    if (
        plan is not None
        and plan[0] == worker.generation
        and worker.current_pg_context() is None
    ):
        # Hot path: every option was resolved ONCE for this (function,
        # session) pair — a 20k/s submit loop re-derives nothing. Only
        # an inherited placement-group capture context (dynamic,
        # per-executing-task) forces the full resolution below.
        _, func_key, name, num_returns, resources, max_retries = plan
        refs = worker.submit_task(
            func_key,
            _flatten_args(args, kwargs),
            name=name,
            num_returns=num_returns,
            resources=resources,
            max_retries=max_retries,
        )
        return refs[0] if num_returns == 1 else refs
    opts = rf.task_options
    func_key = _export_cached(rf.underlying, rf, "_exported_key", worker)
    num_returns = opts.get("num_returns", 1)
    resources = _task_resources(opts, default_cpu=1.0)
    pg_context = None
    if opts.get("_skip_pg_rewrite"):
        strategy = _strategy(opts)
    else:
        resources, strategy, pg_context = _resolve_placement(
            opts, resources, worker
        )
    _validate_num_returns(num_returns)
    name = opts.get("name") or rf.underlying.__name__
    max_retries = opts.get("max_retries", worker.config.task_max_retries)
    runtime_env = prepare_runtime_env(opts.get("runtime_env"), worker)
    if (
        not strategy
        and pg_context is None
        and runtime_env is None
        and not opts.get("_skip_pg_rewrite")
        and isinstance(num_returns, int)
    ):
        # Static options: memoize the resolved plan for this session
        # (generation-keyed like _exported_key, so a dead worker's
        # plan never outlives shutdown()+init()).
        rf._submit_plan = (
            worker.generation, func_key, name, num_returns,
            resources, max_retries,
        )
    refs = worker.submit_task(
        func_key,
        _flatten_args(args, kwargs),
        # name= is a display-name override (reference: task options
        # name); the option-key universe lives in _private/options.py.
        name=name,
        num_returns=num_returns,
        resources=resources,
        max_retries=max_retries,
        scheduling_strategy=strategy,
        pg_context=pg_context,
        runtime_env=runtime_env,
    )
    return _generator_or_refs(refs, num_returns, worker)


def _validate_num_returns(num_returns) -> None:
    if isinstance(num_returns, str):
        if num_returns not in ("dynamic", "streaming"):
            raise ValueError(
                'num_returns must be an int, "dynamic", or "streaming"'
            )
    elif not isinstance(num_returns, int) or num_returns < 1:
        raise ValueError(f"bad num_returns: {num_returns!r}")


def _generator_or_refs(refs, num_returns, worker):
    """Map declared returns to the user-facing handle (reference:
    remote_function.py:385-391 — "streaming" hands back a generator
    immediately; "dynamic" hands back one ref whose value resolves to
    the generator once the task finishes)."""
    if num_returns == "streaming":
        from ..object_ref import ObjectRefGenerator

        # The generator must keep the submit-returned primary ref
        # alive: it holds the owner-side future __next__ waits on.
        return ObjectRefGenerator(
            refs[0].id().task_id(), owner=worker, primary_ref=refs[0]
        )
    if num_returns == "dynamic":
        return refs[0]
    return refs[0] if num_returns == 1 else refs


def create_actor(ac: ActorClass, args: tuple, kwargs: dict) -> ActorHandle:
    worker = _require_worker()
    opts = ac.actor_options
    class_key = _export_cached(ac.underlying, ac, "_exported_key", worker)
    # Named concurrency groups (reference: core_worker/transport/
    # concurrency_group_manager.h): each group is an independent
    # executor of the given width; methods without a group run in the
    # default pool (width = max_concurrency).
    concurrency_groups = opts.get("concurrency_groups") or {}
    for gname, width in concurrency_groups.items():
        if not isinstance(gname, str) or not gname:
            raise ValueError(
                f"concurrency group names must be non-empty strings: "
                f"{gname!r}"
            )
        if not isinstance(width, int) or width < 1:
            raise ValueError(
                f"concurrency group {gname!r} needs a positive int "
                f"width, got {width!r}"
            )
    # @rt.method definition-time defaults, resolved once here so every
    # handle (including deserialized ones) sees them via the meta.
    method_defaults = {}
    for mname in ac.method_names():
        fn = getattr(ac.underlying, mname, None)
        mopts = getattr(fn, "__rt_method_options__", None)
        if mopts:
            group = mopts.get("concurrency_group")
            if group is not None and group not in concurrency_groups:
                raise ValueError(
                    f"method {mname!r} names unknown concurrency "
                    f"group {group!r} (declared: "
                    f"{sorted(concurrency_groups)})"
                )
            method_defaults[mname] = dict(mopts)
    meta = {
        "class_name": ac.underlying.__name__,
        "methods": ac.method_names(),
        "class_key": class_key,
        "concurrency_groups": concurrency_groups,
        "method_defaults": method_defaults,
    }
    # Default actors require 1 CPU to *schedule* but hold 0 for their
    # lifetime (reference: ray_option_utils.py actor defaults —
    # DEFAULT_ACTOR_CREATION_CPU_SIMPLE=0; the 1 CPU gates placement
    # and is released once the actor is up, so more default actors than
    # node CPUs still come up). Explicitly-specified resources are held
    # for the actor's lifetime; an EXPLICIT num_cpus=0 yields {} —
    # schedulable anywhere in any number.
    default_resources = (
        opts.get("num_cpus") is None
        and not opts.get("num_tpus")
        and not opts.get("resources")
    )
    resources, strategy, pg_context = _resolve_placement(
        opts, _task_resources(opts, default_cpu=1.0), worker
    )
    # A PG-targeted actor occupies its bundle slot for its lifetime
    # even with default resources (the rewritten bundle-scoped CPU is
    # the slot), so only non-PG default actors release after placement.
    release_after_up = default_resources and resources == {"CPU": 1.0}
    actor_id = worker.create_actor(
        class_key,
        _flatten_args(args, kwargs),
        class_name=ac.underlying.__name__,
        name=opts.get("name"),
        namespace=opts.get("namespace") or worker.namespace,
        resources=resources,
        max_restarts=opts.get("max_restarts", 0),
        max_concurrency=int(opts.get("max_concurrency", 1)),
        concurrency_groups=concurrency_groups,
        handle_meta=meta,
        scheduling_strategy=strategy,
        pg_context=pg_context,
        runtime_env=prepare_runtime_env(
            opts.get("runtime_env"), worker
        ),
        release_creation_resources=release_after_up,
    )
    return ActorHandle(actor_id, meta)


def submit_actor_method(
    handle: ActorHandle,
    method: str,
    args: tuple,
    kwargs: dict,
    num_returns=1,
    concurrency_group=None,
):
    worker = _require_worker()
    _validate_num_returns(num_returns)
    if concurrency_group is not None:
        declared = handle._meta.get("concurrency_groups")
        # Meta from older handles may lack the key; validate when the
        # declaration is known, else let the worker fall back to the
        # default pool.
        if declared is not None and concurrency_group not in declared:
            raise ValueError(
                f"unknown concurrency group {concurrency_group!r} "
                f"(actor declares: {sorted(declared)})"
            )
    refs = worker.submit_actor_task(
        handle.actor_id,
        method,
        _flatten_args(args, kwargs),
        num_returns=num_returns,
        concurrency_group=concurrency_group,
    )
    return _generator_or_refs(refs, num_returns, worker)
