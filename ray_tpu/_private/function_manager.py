"""Function/actor-class export through the control-plane KV.

Same protocol as the reference (reference:
python/ray/_private/function_manager.py:58 — driver pickles the
function with cloudpickle, exports it into the GCS KV under a digest
key; executing workers lazily fetch + unpickle + cache, :196 export,
:265 fetch_and_register).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict

import cloudpickle

_NS = "fn"


class FunctionManager:
    def __init__(self, rpc_client):
        self._client = rpc_client
        self._exported: set = set()
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def export(self, obj: Callable | type) -> str:
        """Pickle and upload; returns the KV key (content digest)."""
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha256(blob).hexdigest()[:32]
        with self._lock:
            if key in self._exported:
                return key
        self._client.call(
            "kv_put", ns=_NS, key=key, value=blob, overwrite=False
        )
        with self._lock:
            self._exported.add(key)
            self._cache[key] = obj
        return key

    def fetch(self, key: str) -> Any:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        reply = self._client.call("kv_get", ns=_NS, key=key)
        blob = reply.get("value")
        if blob is None:
            raise KeyError(f"function {key} not found in KV")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
