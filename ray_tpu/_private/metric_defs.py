"""Central core-metrics registry.

Reference: src/ray/stats/metric_defs.cc:46-260 — ~60 gauges/counters
defined in ONE table (tasks, actors, objects, scheduler, gRPC, io
loop) so operators learn a single namespace. The TPU-native runtime
does the same in one module: `CORE_METRICS` declares every metric,
`CoreCounters` holds the monotonic event counters the daemon bumps at
the few places things happen, and `collect(daemon)` computes the
point-in-time gauges straight off daemon state at scrape time (pull
model — zero steady-state cost, unlike the reference's push-through-
agent pipeline).

Per-node metrics ride heartbeats to the head (a ~60-float dict every
heartbeat); the head keeps the latest snapshot per node and serves the
aggregate through `metrics_summary` / the dashboard's Prometheus
endpoint with a `node` label.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

#: Cross-node aggregation for gauges where a sum is a lie; everything
#: else sums (counters always sum).
GAUGE_AGGREGATION: Dict[str, str] = {
    "rt_uptime_s": "max",
    "rt_rpc_queue_lag_ms": "mean",
    "rt_rpc_queue_lag_max_ms": "max",
}

#: name -> (kind, unit, description). Names are Prometheus-safe.
CORE_METRICS: Dict[str, tuple] = {
    # -- tasks (reference: metric_defs.cc tasks category) ------------
    "rt_tasks_queued": ("gauge", "tasks", "Tasks waiting in the local scheduler queue"),
    "rt_tasks_running": ("gauge", "tasks", "Tasks currently executing on leased workers"),
    "rt_tasks_infeasible": ("gauge", "tasks", "Tasks no live node can satisfy"),
    "rt_tasks_finished_total": ("counter", "tasks", "Tasks completed successfully"),
    "rt_tasks_failed_total": ("counter", "tasks", "Tasks that raised or died"),
    "rt_tasks_retried_total": ("counter", "tasks", "Task retry resubmissions"),
    "rt_task_events_buffered": ("gauge", "events", "Task state events held for the state API"),
    # -- actors ------------------------------------------------------
    "rt_actors_alive": ("gauge", "actors", "Actors in ALIVE state"),
    "rt_actors_restarting": ("gauge", "actors", "Actors mid-restart"),
    "rt_actors_dead": ("gauge", "actors", "Actors permanently dead"),
    "rt_actors_created_total": ("counter", "actors", "Actor creations requested"),
    "rt_actor_restarts_total": ("counter", "actors", "Actor restart attempts"),
    # -- workers -----------------------------------------------------
    "rt_workers_alive": ("gauge", "workers", "Registered worker processes"),
    "rt_workers_spawning": ("gauge", "workers", "Workers being spawned (startup gate)"),
    "rt_workers_started_total": ("counter", "workers", "Worker processes started"),
    "rt_worker_crashes_total": ("counter", "workers", "Workers that died unexpectedly"),
    "rt_workers_oom_killed_total": ("counter", "workers", "Workers killed by the memory monitor"),
    # -- leases / scheduler ------------------------------------------
    "rt_leases_active": ("gauge", "leases", "Outstanding worker leases"),
    "rt_lease_requests_total": ("counter", "leases", "Worker-lease requests handled"),
    "rt_placement_groups": ("gauge", "groups", "Placement groups registered (head)"),
    # -- objects / store ---------------------------------------------
    "rt_objects_local": ("gauge", "objects", "Objects tracked by this node"),
    "rt_object_store_bytes_used": ("gauge", "bytes", "Shared-memory arena bytes in use"),
    "rt_object_store_bytes_capacity": ("gauge", "bytes", "Shared-memory arena capacity"),
    "rt_object_store_objects": ("gauge", "objects", "Objects resident in the local arena"),
    "rt_objects_spilled": ("gauge", "objects", "Objects currently spilled to disk"),
    "rt_spilled_bytes": ("gauge", "bytes", "Bytes currently spilled to disk"),
    "rt_object_spills_total": ("counter", "spills", "Objects written to spill storage"),
    "rt_object_restores_total": ("counter", "restores", "Spilled objects restored into the arena"),
    "rt_object_pulls_total": ("counter", "pulls", "Cross-node object pulls started"),
    "rt_object_pulls_aborted_total": ("counter", "pulls", "Cross-node pulls that died mid-flight (source gone/evicted); counted here, never billed as transferred bytes"),
    "rt_object_pull_chunks_total": ("counter", "chunks", "Object chunks fetched from remote nodes"),
    "rt_object_pushes_total": ("counter", "pushes", "Object chunks served to remote nodes"),
    # -- control plane (head) ----------------------------------------
    "rt_nodes_alive": ("gauge", "nodes", "Live daemons in the cluster (head)"),
    "rt_nodes_dead": ("gauge", "nodes", "Daemons marked dead (head)"),
    "rt_jobs": ("gauge", "jobs", "Jobs registered (head)"),
    "rt_heartbeats_total": ("counter", "beats", "Heartbeats processed (head)"),
    "rt_kv_keys": ("gauge", "keys", "Internal KV entries (head)"),
    "rt_pubsub_subscribers": ("gauge", "subs", "Live pubsub subscriptions"),
    # -- rpc / event loop (reference: io_context_event_loop_lag_ms) --
    "rt_rpc_requests_total": ("counter", "rpcs", "RPC frames dispatched"),
    "rt_rpc_errors_total": ("counter", "rpcs", "RPC handlers that raised"),
    "rt_rpc_queue_lag_ms": ("gauge", "ms", "Mean handler queueing delay (lifetime; request-weighted across nodes)"),
    "rt_rpc_queue_lag_max_ms": ("gauge", "ms", "Max handler queueing delay observed (lifetime)"),
    # -- process -----------------------------------------------------
    "rt_uptime_s": ("gauge", "s", "Daemon uptime"),
    "rt_rss_mb": ("gauge", "MiB", "Daemon resident set size"),
}

#: Descriptions for metrics that ride the util/metrics._Buffer pipe
#: (pushed by workers, folded into the head's aggregate table) rather
#: than being collect()ed off daemon state. Kept here so the whole
#: namespace is documented in ONE module and `/metrics` renders HELP
#: lines for them; absence from this table is fine (user metrics),
#: it just means no HELP line.
PIPE_METRICS: Dict[str, tuple] = {
    # -- XLA layer (_private/compile_watch.py) -----------------------
    "rt_jax_compiles_total": (
        "counter", "compiles",
        "XLA compilations recorded per program (label: program name "
        "only — shape digests stay in the diagnostic ring)",
    ),
    "rt_jax_compile_ms": (
        "histogram", "ms",
        "Duration of each recorded XLA compilation, per program",
    ),
    "rt_hbm_bytes_in_use": (
        "gauge", "bytes",
        "Device HBM bytes in use, per reporting rank "
        "(device.memory_stats(); absent on CPU backends)",
    ),
    "rt_hbm_peak_bytes": (
        "gauge", "bytes",
        "Peak device HBM bytes in use, per reporting rank",
    ),
    "rt_hbm_bytes_limit": (
        "gauge", "bytes",
        "Device HBM capacity visible to the reporting rank",
    ),
}


class CoreCounters:
    """Monotonic event counters; one instance per daemon process.
    Increments take a lock: getattr/setattr read-modify-write from
    concurrent RPC pool threads would permanently lose counts
    otherwise. Reads stay lock-free (a torn read at scrape
    granularity is harmless; a lost write is forever)."""

    _NAMES = (
        "tasks_finished",
        "tasks_failed",
        "tasks_retried",
        "actors_created",
        "actor_restarts",
        "workers_started",
        "oom_kills",
        "lease_requests",
        "pulls",
        "pulls_aborted",
        "pull_chunks",
        "pushes",
        "heartbeats",
        "spills",
        "restores",
    )

    def __init__(self):
        self._bump_lock = threading.Lock()
        for name in self._NAMES:
            setattr(self, name, 0)

    def bump(self, name: str, n: int = 1) -> None:
        with self._bump_lock:
            setattr(self, name, getattr(self, name) + n)

    def as_dict(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in self._NAMES}


def _rss_mb() -> float:
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / (1024 * 1024)
    except Exception:  # noqa: BLE001 — metrics must not raise
        return 0.0


def collect(daemon) -> Dict[str, float]:
    """Scrape this daemon's core metrics. Reads daemon state
    defensively: a missing structure reports 0, never raises."""
    out: Dict[str, float] = {}
    counters = getattr(daemon, "core_counters", None)
    c = counters.as_dict() if counters is not None else {}

    def safe(fn, default=0.0):
        try:
            return float(fn())
        except Exception:  # noqa: BLE001
            return default

    # tasks / workers / leases (daemon-local, under its lock where
    # cheap; scrapes tolerate slightly torn reads)
    out["rt_tasks_queued"] = safe(
        lambda: daemon.scheduler.queued_count()
    )
    out["rt_tasks_running"] = safe(lambda: len(daemon.leases))
    out["rt_tasks_infeasible"] = safe(
        lambda: len(daemon._infeasible)
    )
    out["rt_workers_alive"] = safe(lambda: len(daemon.workers))
    out["rt_workers_spawning"] = safe(lambda: daemon._spawning)
    out["rt_leases_active"] = safe(lambda: len(daemon.leases))
    out["rt_objects_local"] = safe(lambda: len(daemon.objects))

    # store / spill
    try:
        info = daemon.store.size_info()
        out["rt_object_store_bytes_used"] = float(
            info.get("used", 0)
        )
        out["rt_object_store_bytes_capacity"] = float(
            info.get("capacity", 0)
        )
        out["rt_object_store_objects"] = float(
            info.get("num_objects", 0)
        )
    except Exception:  # noqa: BLE001
        pass
    try:
        if daemon.spill is not None:
            stats = daemon.spill.stats()
            out["rt_objects_spilled"] = float(
                stats.get("spilled_objects", 0)
            )
            out["rt_spilled_bytes"] = float(
                stats.get("spilled_bytes", 0)
            )
    except Exception:  # noqa: BLE001
        pass

    # head-only control plane
    if getattr(daemon, "is_head", False):
        try:
            summary = daemon.control.summary()
            alive = summary.get("alive_nodes", 0)
            out["rt_nodes_alive"] = float(alive)
            out["rt_nodes_dead"] = float(
                summary.get("nodes", 0) - alive
            )
            out["rt_jobs"] = float(summary.get("jobs", 0))
            out["rt_placement_groups"] = float(
                summary.get("placement_groups", 0)
            )
            actors = daemon.control.actors.values()
            states: Dict[str, int] = {}
            for a in actors:
                states[a.state] = states.get(a.state, 0) + 1
            out["rt_actors_alive"] = float(states.get("ALIVE", 0))
            out["rt_actors_restarting"] = float(
                states.get("RESTARTING", 0)
            )
            out["rt_actors_dead"] = float(states.get("DEAD", 0))
            out["rt_kv_keys"] = safe(
                lambda: sum(
                    len(ns) for ns in daemon.control.kv.values()
                )
            )
            out["rt_task_events_buffered"] = safe(
                lambda: len(daemon.control.task_events)
            )
        except Exception:  # noqa: BLE001
            pass

    # rpc event stats -> loop-lag gauges
    try:
        from .event_stats import stats as event_stats

        snap = event_stats().snapshot()
        total = sum(s["count"] for s in snap.values())
        errors = sum(s["errors"] for s in snap.values())
        queue_total = sum(
            s["mean_queue_ms"] * s["count"] for s in snap.values()
        )
        out["rt_rpc_requests_total"] = float(total)
        out["rt_rpc_errors_total"] = float(errors)
        out["rt_rpc_queue_lag_ms"] = (
            queue_total / total if total else 0.0
        )
        out["rt_rpc_queue_lag_max_ms"] = max(
            (s["max_queue_ms"] for s in snap.values()),
            default=0.0,
        )
    except Exception:  # noqa: BLE001
        pass

    # counters
    out["rt_tasks_finished_total"] = float(c.get("tasks_finished", 0))
    out["rt_tasks_failed_total"] = float(c.get("tasks_failed", 0))
    out["rt_tasks_retried_total"] = float(c.get("tasks_retried", 0))
    out["rt_actors_created_total"] = float(c.get("actors_created", 0))
    out["rt_actor_restarts_total"] = float(c.get("actor_restarts", 0))
    out["rt_workers_started_total"] = float(c.get("workers_started", 0))
    out["rt_worker_crashes_total"] = float(
        getattr(daemon, "_spawn_crash_total", 0)
    )
    out["rt_workers_oom_killed_total"] = float(c.get("oom_kills", 0))
    out["rt_lease_requests_total"] = float(c.get("lease_requests", 0))
    out["rt_object_spills_total"] = float(c.get("spills", 0))
    out["rt_object_restores_total"] = float(c.get("restores", 0))
    out["rt_object_pulls_total"] = float(c.get("pulls", 0))
    out["rt_object_pulls_aborted_total"] = float(c.get("pulls_aborted", 0))
    out["rt_object_pull_chunks_total"] = float(c.get("pull_chunks", 0))
    out["rt_object_pushes_total"] = float(c.get("pushes", 0))
    out["rt_heartbeats_total"] = float(c.get("heartbeats", 0))

    out["rt_uptime_s"] = time.time() - getattr(
        daemon, "started_at", time.time()
    )
    out["rt_rss_mb"] = _rss_mb()
    return out
