"""Per-handler RPC event statistics.

Reference: src/ray/common/event_stats.cc — every asio handler records
count, queueing delay, and execution time into a global registry that
surfaces in the debug state dump. The equivalent here instruments the
RPC server's dispatch path (rpc.py): queueing delay is the time a
frame waits between the hub thread enqueueing it and a pool thread
starting its handler — the direct analog of asio loop lag, and the
first number to look at when the daemon feels sluggish (is one
handler slow, or is the pool starved?).

Costs one monotonic read per enqueue and two per dispatch (~100 ns);
always on.
"""

from __future__ import annotations

import threading
from typing import Dict


class _HandlerStat:
    __slots__ = (
        "count",
        "total_exec_s",
        "max_exec_s",
        "total_queue_s",
        "max_queue_s",
        "errors",
    )

    def __init__(self):
        self.count = 0
        self.total_exec_s = 0.0
        self.max_exec_s = 0.0
        self.total_queue_s = 0.0
        self.max_queue_s = 0.0
        self.errors = 0


class EventStats:
    """Registry of per-handler timing stats for one process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, _HandlerStat] = {}

    def record(
        self,
        name: str,
        queue_s: float,
        exec_s: float,
        error: bool = False,
    ) -> None:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _HandlerStat()
            stat.count += 1
            stat.total_exec_s += exec_s
            stat.total_queue_s += queue_s
            if exec_s > stat.max_exec_s:
                stat.max_exec_s = exec_s
            if queue_s > stat.max_queue_s:
                stat.max_queue_s = queue_s
            if error:
                stat.errors += 1

    def snapshot(self) -> Dict[str, dict]:
        """{handler: {count, mean/max exec ms, mean/max queue ms,
        errors}}, sorted by cumulative execution time (the reference
        dump sorts the same way: the top row is where the loop's time
        went)."""
        with self._lock:
            items = list(self._stats.items())
        out = {}
        for name, s in sorted(
            items, key=lambda kv: -kv[1].total_exec_s
        ):
            out[name] = {
                "count": s.count,
                "mean_exec_ms": round(
                    s.total_exec_s / s.count * 1e3, 3
                ),
                "max_exec_ms": round(s.max_exec_s * 1e3, 3),
                "total_exec_ms": round(s.total_exec_s * 1e3, 1),
                "mean_queue_ms": round(
                    s.total_queue_s / s.count * 1e3, 3
                ),
                "max_queue_ms": round(s.max_queue_s * 1e3, 3),
                "errors": s.errors,
            }
        return out


_GLOBAL = EventStats()


def stats() -> EventStats:
    return _GLOBAL
