"""Typed wire schema + protocol versioning for the RPC plane.

Reference behavior matched: src/ray/protobuf/*.proto — every RPC has a
typed message schema, and incompatible peers fail cleanly. Here:

* The frame ENVELOPE (method, correlation id, push channel, version)
  is protobuf (`protocol.proto` / `protocol_pb2.py`).
* The protocol version is negotiated at connection handshake (the
  server's nonce frame carries it) and stamped on every frame.
* Per-method argument schemas (`SCHEMAS`) are validated server-side
  before dispatch: unknown methods and mistyped/missing fields produce
  a clean typed error instead of a KeyError deep inside a handler.
  tests/test_wire_schema.py asserts the registry covers every method
  the daemon registers.

The argument payload itself stays a pickled dict behind the HMAC
(authenticated before any bytes reach the deserializer) — a documented
trade for Python-only workers and pickle5 zero-copy buffers.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Tuple

from .protocol_pb2 import Frame

PROTOCOL_VERSION = 1


class ProtocolVersionError(Exception):
    """Peer speaks a different wire protocol version."""


class SchemaError(Exception):
    """Message failed per-method schema validation."""


# -- frame codec -------------------------------------------------------


import struct as _struct

_ENV_LEN = _struct.Struct(">I")


def encode_frame_buffers(msg: Dict[str, Any]) -> list:
    """Internal message dict -> list of wire buffers:
    [env-len + envelope + pickled body, oob buffer, oob buffer, ...]

    pickle protocol 5 hands large binary values (PickleBuffer-backed
    objects: numpy arrays, PickleBuffer wrappers) to the
    buffer_callback instead of copying them into the stream; their
    lengths ride in the envelope (Frame.buffer_lens) and the raw
    buffers are scatter-gathered onto the socket AS-IS — the
    object-transfer fast path (reference: PushManager chunk bytes,
    minus the protobuf-copy tax)."""
    body = {
        k: v
        for k, v in msg.items()
        if k not in ("_method", "_mid", "_push")
    }
    oob: list = []
    body_bytes = (
        pickle.dumps(body, protocol=5, buffer_callback=oob.append)
        if body
        else b""
    )
    raw = [buf.raw() for buf in oob]
    frame = Frame(
        version=PROTOCOL_VERSION,
        method=msg.get("_method", ""),
        mid=msg.get("_mid") or 0,
        channel=msg.get("_push", ""),
        buffer_lens=[len(r) for r in raw],
    )
    env = frame.SerializeToString()
    return [
        b"".join((_ENV_LEN.pack(len(env)), env, body_bytes)),
        *raw,
    ]


def encode_frame(msg: Dict[str, Any]) -> bytes:
    """Contiguous-frame convenience (tests, fuzzing); the transport
    uses encode_frame_buffers for vectored sends."""
    return b"".join(
        bytes(b) if not isinstance(b, bytes) else b
        for b in encode_frame_buffers(msg)
    )


def decode_frame(data) -> Dict[str, Any]:
    """Frame bytes -> internal message dict. Raises
    ProtocolVersionError on version mismatch (belt-and-braces: the
    handshake already rejects such peers)."""
    view = memoryview(data)
    (env_len,) = _ENV_LEN.unpack_from(view, 0)
    frame = Frame()
    frame.ParseFromString(bytes(view[4 : 4 + env_len]))
    if frame.version != PROTOCOL_VERSION:
        raise ProtocolVersionError(
            f"peer protocol v{frame.version}, this node speaks "
            f"v{PROTOCOL_VERSION}"
        )
    rest = view[4 + env_len :]
    buffers = []
    if frame.buffer_lens:
        # Out-of-band buffers sit after the body; hand pickle
        # zero-copy slices of the receive buffer.
        tail_len = sum(frame.buffer_lens)
        body = rest[: len(rest) - tail_len]
        offset = len(body)
        for blen in frame.buffer_lens:
            buffers.append(rest[offset : offset + blen])
            offset += blen
    else:
        body = rest
    msg: Dict[str, Any] = (
        pickle.loads(body, buffers=buffers) if len(body) else {}
    )
    if frame.method:
        msg["_method"] = frame.method
    msg["_mid"] = frame.mid
    if frame.channel:
        msg["_push"] = frame.channel
    return msg


# -- flat task-spec codec ----------------------------------------------
#
# Task specs are flat dicts of bytes/str/int/float plus two structured
# hot fields (`args`, `returns`) and a handful of cold nested options
# (task_spec.py documents the shape). The batch submit path encodes
# each spec with this dedicated codec instead of pickling the dict, so
# `pickle.dumps` leaves the per-task critical path: the hot fields of
# the common shape ride one struct-packed header + length-prefixed
# blobs, and only the rare cold fields (scheduling_strategy,
# runtime_env, handle_meta, ...) fall back to an embedded pickle.
# A batch frame is the blobs joined with u32 length prefixes — the
# outer RPC pickle then moves ONE bytes object (a memcpy), not N spec
# dicts. `SPEC_MAGIC` is the frame kind byte; bump it when the layout
# changes (decode refuses unknown kinds cleanly).

SPEC_MAGIC = 0xF5  # flat-codec task-spec frame kind, layout v1

#: Field-id table: spec keys with stable 1-byte ids. Order is
#: append-only (ids are wire format); `ray_tpu check` RT104 and
#: tests/test_wire_schema.py keep this table in sync with the fields
#: the submit paths actually ship.
SPEC_FIELDS = [
    # hot header fields (encoded positionally, listed for the table)
    "task_id", "job_id", "kind", "name", "function_key", "args",
    "returns", "resources", "max_retries",
    # tagged tail fields
    "actor_id", "method", "ns_ctx", "num_returns_mode",
    "concurrency_group", "max_restarts", "max_concurrency",
    "release_creation_resources", "namespace", "class_name",
    "handle_meta", "scheduling_strategy", "pg_context", "runtime_env",
    "trace_ctx", "_retries_left", "concurrency_groups",
]
_SPEC_FID = {name: i for i, name in enumerate(SPEC_FIELDS)}
_HOT_FIELDS = frozenset(SPEC_FIELDS[:9])

_SPEC_KINDS = ["normal", "actor_creation", "actor_task", "lease"]
_KIND_CODE = {k: i for i, k in enumerate(_SPEC_KINDS)}

# magic, kind, task_id, job_id, max_retries (signed: -1 = infinite),
# name_len, fkey_len, n_args, n_returns, n_resources
_HOT = _struct.Struct("<BB16s4siHHIHB")
_U32 = _struct.Struct("<I")
_I64 = _struct.Struct("<q")
_F64 = _struct.Struct("<d")

#: Precomputed (field-id, type-tag) prefixes for the tagged tail.
_TAIL_PFX = {
    (name, tag): bytes((fid, tag))
    for name, fid in _SPEC_FID.items()
    for tag in b"NBSTFIDP"
}


class SpecCodecError(Exception):
    """Blob is not a valid flat-codec task spec."""


def encode_spec(spec: Dict[str, Any]) -> bytes:
    """Task-spec dict -> flat blob (no pickle for the hot fields)."""
    name = spec.get("name") or ""
    name_b = name.encode()
    fkey_b = (spec.get("function_key") or "").encode()
    args = spec.get("args") or ()
    returns = spec.get("returns") or ()
    resources = spec.get("resources")
    res_items = list(resources.items()) if resources else []
    parts = [
        _HOT.pack(
            SPEC_MAGIC,
            _KIND_CODE[spec["kind"]],
            spec["task_id"],
            spec["job_id"],
            spec.get("max_retries") or 0,
            len(name_b),
            len(fkey_b),
            len(args),
            len(returns),
            len(res_items),
        ),
        name_b,
        fkey_b,
    ]
    ap = parts.append
    u32p = _U32.pack
    for kind, payload in args:
        ap(b"\x00" if kind == "inline" else b"\x01")
        ap(u32p(len(payload)))
        ap(payload)
    for ret in returns:
        ap(bytes((len(ret),)))
        ap(ret)
    for rk, rv in res_items:
        rkb = rk.encode()
        ap(bytes((len(rkb),)))
        ap(rkb)
        ap(_F64.pack(rv))
    for key, v in spec.items():
        if key in _HOT_FIELDS:
            continue
        t = v.__class__
        if v is None:
            ap(_TAIL_PFX[(key, 78)])  # N
        elif t is bytes:
            ap(_TAIL_PFX[(key, 66)])  # B
            ap(u32p(len(v)))
            ap(v)
        elif t is str:
            vb = v.encode()
            ap(_TAIL_PFX[(key, 83)])  # S
            ap(u32p(len(vb)))
            ap(vb)
        elif t is bool:
            ap(_TAIL_PFX[(key, 84 if v else 70)])  # T / F
        elif t is int:
            ap(_TAIL_PFX[(key, 73)])  # I
            ap(_I64.pack(v))
        elif t is float:
            ap(_TAIL_PFX[(key, 68)])  # D
            ap(_F64.pack(v))
        else:
            # Cold nested option (scheduling_strategy, runtime_env,
            # handle_meta, ...): embedded pickle, length-prefixed —
            # never on the hot normal-task shape.
            pb = pickle.dumps(v, protocol=5)
            ap(_TAIL_PFX[(key, 80)])  # P
            ap(u32p(len(pb)))
            ap(pb)
    return b"".join(parts)


def decode_spec(data: bytes) -> Dict[str, Any]:
    """Flat blob -> task-spec dict. Raises SpecCodecError on a frame
    that is not a v1 flat spec (unknown magic/kind/field)."""
    try:
        (
            magic, kind_code, task_id, job_id, max_retries,
            name_len, fkey_len, n_args, n_returns, n_res,
        ) = _HOT.unpack_from(data, 0)
        if magic != SPEC_MAGIC:
            raise SpecCodecError(f"bad spec magic {magic:#x}")
        pos = _HOT.size
        name = data[pos:pos + name_len].decode()
        pos += name_len
        fkey = data[pos:pos + fkey_len].decode()
        pos += fkey_len
        u32uf = _U32.unpack_from
        args = []
        for _ in range(n_args):
            akind = "inline" if data[pos] == 0 else "ref"
            (ln,) = u32uf(data, pos + 1)
            pos += 5
            args.append((akind, data[pos:pos + ln]))
            pos += ln
        returns = []
        for _ in range(n_returns):
            ln = data[pos]
            pos += 1
            returns.append(data[pos:pos + ln])
            pos += ln
        resources = {}
        for _ in range(n_res):
            kl = data[pos]
            pos += 1
            rk = data[pos:pos + kl].decode()
            pos += kl
            (rv,) = _F64.unpack_from(data, pos)
            pos += 8
            resources[rk] = rv
        spec = {
            "task_id": task_id,
            "job_id": job_id,
            "kind": _SPEC_KINDS[kind_code],
            "name": name,
            "function_key": fkey,
            "args": args,
            "returns": returns,
            "resources": resources,
            "max_retries": max_retries,
        }
        end = len(data)
        fields = SPEC_FIELDS
        while pos < end:
            key = fields[data[pos]]
            tag = data[pos + 1]
            pos += 2
            if tag == 78:  # N
                spec[key] = None
            elif tag == 66:  # B
                (ln,) = u32uf(data, pos)
                pos += 4
                spec[key] = data[pos:pos + ln]
                pos += ln
            elif tag == 83:  # S
                (ln,) = u32uf(data, pos)
                pos += 4
                spec[key] = data[pos:pos + ln].decode()
                pos += ln
            elif tag == 84:  # T
                spec[key] = True
            elif tag == 70:  # F
                spec[key] = False
            elif tag == 73:  # I
                (spec[key],) = _I64.unpack_from(data, pos)
                pos += 8
            elif tag == 68:  # D
                (spec[key],) = _F64.unpack_from(data, pos)
                pos += 8
            elif tag == 80:  # P
                (ln,) = u32uf(data, pos)
                pos += 4
                spec[key] = pickle.loads(data[pos:pos + ln])
                pos += ln
            else:
                raise SpecCodecError(f"unknown tail tag {tag:#x}")
        return spec
    except SpecCodecError:
        raise
    except Exception as e:
        raise SpecCodecError(f"malformed spec blob: {e!r}") from e


def encode_spec_batch(blobs) -> bytes:
    """Join pre-encoded spec blobs into one length-prefixed frame
    payload: the outer RPC pickle moves a single bytes object."""
    pack = _U32.pack
    return b"".join(
        part for blob in blobs for part in (pack(len(blob)), blob)
    )


def split_spec_batch(frame) -> list:
    """Length-prefixed batch payload -> list of raw blobs (framing
    errors raise SpecCodecError; per-blob decode stays the caller's so
    one malformed spec can fail alone instead of killing the batch)."""
    blobs = []
    pos = 0
    end = len(frame)
    u32uf = _U32.unpack_from
    try:
        while pos < end:
            (ln,) = u32uf(frame, pos)
            pos += 4
            if pos + ln > end:
                raise SpecCodecError("truncated batch frame")
            blobs.append(frame[pos:pos + ln])
            pos += ln
    except SpecCodecError:
        raise
    except Exception as e:
        raise SpecCodecError(f"malformed batch frame: {e!r}") from e
    return blobs


def decode_spec_batch(frame) -> list:
    """Length-prefixed batch payload -> list of spec dicts."""
    return [decode_spec(blob) for blob in split_spec_batch(frame)]


# -- per-method argument schemas ---------------------------------------
#
# field spec: name -> type or tuple of accepted types. A leading "?"
# marks the field optional. `dict`/`list` cover nested structures whose
# internals the handlers own. Every method registered on the daemon or
# the worker's direct server MUST appear here (enforced by test).

_num = (int, float)

SCHEMAS: Dict[str, Dict[str, Any]] = {
    # registration / lifecycle
    "register_client": {
        "role": str, "pid": int, "?is_tpu": bool,
        "?direct_address": (str, type(None)), "?entrypoint": str,
    },
    "register_node": {
        "node_id": bytes, "address": str, "resources": dict,
        "?labels": (dict, type(None)),
    },
    "node_heartbeat": {
        "node_id": bytes, "?version": int,
        "?available": (dict, type(None)),
        "?total": (dict, type(None)), "?queued": int,
        "?core_metrics": dict,
    },
    "node_resync": {"node_id": bytes, "actors": list, "objects": list},
    "_disconnect": {},
    "ping": {},
    # direct transport
    "request_lease": {"resources": dict, "?needs_tpu": bool},
    "release_lease": {"lease_id": str},
    "actor_address": {"actor_id": bytes},
    "execute_task": {"spec": dict},
    # Batched direct execution on a leased worker: flat-codec batch
    # payload; the deferred reply carries per-spec outcomes in order.
    "execute_tasks": {"specs": bytes, "count": int},
    # on-demand profiling (reference: dashboard reporter
    # profile_manager — py-spy/memray attach; here in-process)
    "profile": {
        "?kind": str, "?duration_s": _num, "?hz": _num, "?top": int,
        "?start_at": _num,
    },
    "profile_worker": {
        "pid": int, "?kind": str, "?duration_s": _num,
        "?hz": _num, "?top": int, "?node_id": (bytes, type(None)),
        "?start_at": _num,
    },
    # coordinated gang profiling + the head's compile-watch table
    "profile_gang": {
        "?job": (str, type(None)), "?duration_s": _num, "?hz": _num,
    },
    "compile_summary": {},
    # KV
    "kv_put": {
        "key": (str, bytes), "value": bytes, "?ns": str,
        "?overwrite": bool,
    },
    "kv_get": {"key": (str, bytes), "?ns": str},
    "kv_del": {"key": (str, bytes), "?ns": str},
    "kv_keys": {"?prefix": (str, bytes), "?ns": str},
    # object plane
    # Owner-attribution fields on seal/put reports feed the memory
    # ledger: job hex, creating context ("driver"/"task:…"/"actor:…"),
    # and the creator's pid (probed for leak liveness node-locally).
    "put_inline": {
        "oid": bytes, "data": bytes,
        "?owner_job": str, "?owner": str, "?owner_pid": int,
    },
    "object_sealed": {
        "oid": bytes, "size": int, "?node_id": (bytes, type(None)),
        "?owner_job": str, "?owner": str, "?owner_pid": int,
    },
    "seal_error": {"oid": bytes, "error": bytes},
    "get_object": {"oid": bytes},
    # Batched non-blocking get: one round trip resolves N refs (the
    # worker's arg-fetch path); unsealed oids come back as pending
    # markers and the caller falls back to blocking get_object.
    "get_objects": {"oids": list},
    "get_object_meta": {"oid": bytes},
    "pull_object": {"oid": bytes, "?offset": int, "?length": int},
    "delete_object": {"oid": bytes},
    "object_evicted": {"oid": bytes, "?node_id": (bytes, type(None))},
    "spill_request": {"?bytes_needed": int},
    "wait_objects": {
        "oids": list, "num_returns": int,
        "?wait_timeout": (_num + (type(None),)),
    },
    "add_ref": {"oids": list},
    "del_ref": {"oids": list},
    # task plane
    "submit_task": {"spec": dict},
    # Batched submission: `specs` is a flat-codec batch payload
    # (length-prefixed SPEC_MAGIC blobs, see encode_spec_batch) and
    # `count` its spec count; per-spec failures ride back in the reply
    # as {index: error} so error semantics stay per-spec. Ingestion is
    # idempotent by task_id — a retried batch is exactly-once.
    "submit_tasks": {"specs": bytes, "count": int},
    "schedule_task": {"spec": dict},
    "task_finished": {"task_id": bytes, "?had_error": bool},
    "task_done": {
        "task_id": bytes, "?error": (bytes, type(None)),
        "?system_error": (bool, str, type(None)),
    },
    "cancel_task": {"task_id": bytes},
    "cancel_local": {"task_id": bytes},
    "task_event": {"events": list, "?finished": int, "?failed": int},
    "task_counts": {"?finished": int, "?failed": int},
    "span_event": {"spans": list},
    "list_spans": {"?limit": int},
    # actors
    "create_actor": {"spec": dict},
    "submit_actor_task": {"spec": dict},
    "actor_task": {"spec": dict},
    "actor_created": {
        "actor_id": bytes, "node_id": bytes, "?failed": bool,
    },
    "actor_worker_died": {"actor_id": bytes, "?creating": bool},
    "kill_actor": {"actor_id": bytes, "?no_restart": bool},
    "kill_actor_local": {"actor_id": bytes},
    "get_named_actor": {"name": str, "?namespace": str},
    "get_actor_info": {"actor_id": bytes},
    # placement groups
    "create_placement_group": {
        "pg_id": bytes, "bundles": list, "strategy": str,
        "?name": (str, type(None)),
    },
    "placement_group_state": {"pg_id": bytes},
    "placement_group_table": {},
    "remove_placement_group": {"pg_id": bytes},
    "prepare_bundle": {
        "pg_id": bytes, "bundle_index": int, "resources": dict,
    },
    "commit_bundle": {"pg_id": bytes, "bundle_index": int},
    "release_bundle": {"pg_id": bytes, "?bundle_index": int},
    # cluster state / observability
    "cluster_resources": {},
    "available_resources": {},
    "state_summary": {},
    "list_task_events": {"?limit": int},
    "list_nodes": {},
    "list_actors": {},
    "list_objects": {"?limit": int},
    "cluster_load": {},
    "request_resources": {"bundles": list},
    "metrics_record": {
        "records": list,
        "?sender": (str, type(None)),
        "?seq": (int, type(None)),
    },
    "metrics_summary": {},
    # memory ledger: per-node reports up, cluster view down
    "memory_report": {"report": dict},
    "memory_summary": {},
    # data plane (ISSUE 20): transfer matrix + object-location index
    "transfer_summary": {},
    "object_locations": {
        "?oids": list, "?limit": int,
    },
    "metrics_timeseries": {
        "?name": (str, type(None)),
        "?since": _num,
        "?limit": int,
    },
    "event_stats": {},
    # flight recorder / doctor (rings are pulled, never pushed)
    "flight_recorder": {
        "?limit": int, "?kinds": (list, type(None)),
        "?pid": int, "?node_id": (bytes, type(None)),
    },
    "lock_witness": {
        "?pid": int, "?node_id": (bytes, type(None)),
        "?all_workers": bool,
    },
    "inspect": {},
    "worker_inspect": {"?node_id": (bytes, type(None))},
    "step_summary": {"?limit": int, "?records": bool},
    "diagnose": {
        "?hung_task_s": _num, "?straggler_threshold": _num,
        "?capture_stacks": bool, "?limit": int, "?leak_age_s": _num,
        "?compile_storm_threshold": _num,
        "?locality_miss_threshold": _num,
    },
    # pubsub / log streaming
    "subscribe_logs": {"?channels": list},
    "unsubscribe_logs": {},
    "log_batch": {"batches": list, "node": str},
    "publish_event": {"channel": str, "payload": dict},
}


def has_schema(method: str) -> bool:
    """Whether `method` has a registered argument schema. Dispatch
    (rpc.RpcServer._dispatch) warns once per process for methods
    served without one — schema-less dispatch skips typed validation,
    which is exactly the drift `ray_tpu check` (RT104) exists to
    catch."""
    return method in SCHEMAS


def validate(method: str, msg: Dict[str, Any]) -> Optional[str]:
    """Check `msg` against the method's schema. Returns an error
    string, or None when valid. Methods without a registered schema
    pass (the completeness test keeps the registry in sync)."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return None
    for name, types in schema.items():
        optional = name.startswith("?")
        field = name[1:] if optional else name
        if field not in msg:
            if optional:
                continue
            return f"{method}: missing required field {field!r}"
        value = msg[field]
        if not isinstance(value, types):
            expected = (
                types.__name__
                if isinstance(types, type)
                else "/".join(t.__name__ for t in types)
            )
            return (
                f"{method}: field {field!r} expects {expected}, got "
                f"{type(value).__name__}"
            )
    return None
