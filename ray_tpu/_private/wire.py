"""Typed wire schema + protocol versioning for the RPC plane.

Reference behavior matched: src/ray/protobuf/*.proto — every RPC has a
typed message schema, and incompatible peers fail cleanly. Here:

* The frame ENVELOPE (method, correlation id, push channel, version)
  is protobuf (`protocol.proto` / `protocol_pb2.py`).
* The protocol version is negotiated at connection handshake (the
  server's nonce frame carries it) and stamped on every frame.
* Per-method argument schemas (`SCHEMAS`) are validated server-side
  before dispatch: unknown methods and mistyped/missing fields produce
  a clean typed error instead of a KeyError deep inside a handler.
  tests/test_wire_schema.py asserts the registry covers every method
  the daemon registers.

The argument payload itself stays a pickled dict behind the HMAC
(authenticated before any bytes reach the deserializer) — a documented
trade for Python-only workers and pickle5 zero-copy buffers.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Tuple

from .protocol_pb2 import Frame

PROTOCOL_VERSION = 1


class ProtocolVersionError(Exception):
    """Peer speaks a different wire protocol version."""


class SchemaError(Exception):
    """Message failed per-method schema validation."""


# -- frame codec -------------------------------------------------------


import struct as _struct

_ENV_LEN = _struct.Struct(">I")


def encode_frame_buffers(msg: Dict[str, Any]) -> list:
    """Internal message dict -> list of wire buffers:
    [env-len + envelope + pickled body, oob buffer, oob buffer, ...]

    pickle protocol 5 hands large binary values (PickleBuffer-backed
    objects: numpy arrays, PickleBuffer wrappers) to the
    buffer_callback instead of copying them into the stream; their
    lengths ride in the envelope (Frame.buffer_lens) and the raw
    buffers are scatter-gathered onto the socket AS-IS — the
    object-transfer fast path (reference: PushManager chunk bytes,
    minus the protobuf-copy tax)."""
    body = {
        k: v
        for k, v in msg.items()
        if k not in ("_method", "_mid", "_push")
    }
    oob: list = []
    body_bytes = (
        pickle.dumps(body, protocol=5, buffer_callback=oob.append)
        if body
        else b""
    )
    raw = [buf.raw() for buf in oob]
    frame = Frame(
        version=PROTOCOL_VERSION,
        method=msg.get("_method", ""),
        mid=msg.get("_mid") or 0,
        channel=msg.get("_push", ""),
        buffer_lens=[len(r) for r in raw],
    )
    env = frame.SerializeToString()
    return [
        b"".join((_ENV_LEN.pack(len(env)), env, body_bytes)),
        *raw,
    ]


def encode_frame(msg: Dict[str, Any]) -> bytes:
    """Contiguous-frame convenience (tests, fuzzing); the transport
    uses encode_frame_buffers for vectored sends."""
    return b"".join(
        bytes(b) if not isinstance(b, bytes) else b
        for b in encode_frame_buffers(msg)
    )


def decode_frame(data) -> Dict[str, Any]:
    """Frame bytes -> internal message dict. Raises
    ProtocolVersionError on version mismatch (belt-and-braces: the
    handshake already rejects such peers)."""
    view = memoryview(data)
    (env_len,) = _ENV_LEN.unpack_from(view, 0)
    frame = Frame()
    frame.ParseFromString(bytes(view[4 : 4 + env_len]))
    if frame.version != PROTOCOL_VERSION:
        raise ProtocolVersionError(
            f"peer protocol v{frame.version}, this node speaks "
            f"v{PROTOCOL_VERSION}"
        )
    rest = view[4 + env_len :]
    buffers = []
    if frame.buffer_lens:
        # Out-of-band buffers sit after the body; hand pickle
        # zero-copy slices of the receive buffer.
        tail_len = sum(frame.buffer_lens)
        body = rest[: len(rest) - tail_len]
        offset = len(body)
        for blen in frame.buffer_lens:
            buffers.append(rest[offset : offset + blen])
            offset += blen
    else:
        body = rest
    msg: Dict[str, Any] = (
        pickle.loads(body, buffers=buffers) if len(body) else {}
    )
    if frame.method:
        msg["_method"] = frame.method
    msg["_mid"] = frame.mid
    if frame.channel:
        msg["_push"] = frame.channel
    return msg


# -- per-method argument schemas ---------------------------------------
#
# field spec: name -> type or tuple of accepted types. A leading "?"
# marks the field optional. `dict`/`list` cover nested structures whose
# internals the handlers own. Every method registered on the daemon or
# the worker's direct server MUST appear here (enforced by test).

_num = (int, float)

SCHEMAS: Dict[str, Dict[str, Any]] = {
    # registration / lifecycle
    "register_client": {
        "role": str, "pid": int, "?is_tpu": bool,
        "?direct_address": (str, type(None)), "?entrypoint": str,
    },
    "register_node": {
        "node_id": bytes, "address": str, "resources": dict,
        "?labels": (dict, type(None)),
    },
    "node_heartbeat": {
        "node_id": bytes, "?version": int,
        "?available": (dict, type(None)),
        "?total": (dict, type(None)), "?queued": int,
        "?core_metrics": dict,
    },
    "node_resync": {"node_id": bytes, "actors": list, "objects": list},
    "_disconnect": {},
    "ping": {},
    # direct transport
    "request_lease": {"resources": dict, "?needs_tpu": bool},
    "release_lease": {"lease_id": str},
    "actor_address": {"actor_id": bytes},
    "execute_task": {"spec": dict},
    # on-demand profiling (reference: dashboard reporter
    # profile_manager — py-spy/memray attach; here in-process)
    "profile": {
        "?kind": str, "?duration_s": _num, "?hz": _num, "?top": int,
    },
    "profile_worker": {
        "pid": int, "?kind": str, "?duration_s": _num,
        "?hz": _num, "?top": int, "?node_id": (bytes, type(None)),
    },
    # KV
    "kv_put": {
        "key": (str, bytes), "value": bytes, "?ns": str,
        "?overwrite": bool,
    },
    "kv_get": {"key": (str, bytes), "?ns": str},
    "kv_del": {"key": (str, bytes), "?ns": str},
    "kv_keys": {"?prefix": (str, bytes), "?ns": str},
    # object plane
    "put_inline": {"oid": bytes, "data": bytes},
    "object_sealed": {
        "oid": bytes, "size": int, "?node_id": (bytes, type(None)),
    },
    "seal_error": {"oid": bytes, "error": bytes},
    "get_object": {"oid": bytes},
    "get_object_meta": {"oid": bytes},
    "pull_object": {"oid": bytes, "?offset": int, "?length": int},
    "delete_object": {"oid": bytes},
    "object_evicted": {"oid": bytes, "?node_id": (bytes, type(None))},
    "spill_request": {"?bytes_needed": int},
    "wait_objects": {
        "oids": list, "num_returns": int,
        "?wait_timeout": (_num + (type(None),)),
    },
    "add_ref": {"oids": list},
    "del_ref": {"oids": list},
    # task plane
    "submit_task": {"spec": dict},
    "schedule_task": {"spec": dict},
    "task_finished": {"task_id": bytes, "?had_error": bool},
    "task_done": {
        "task_id": bytes, "?error": (bytes, type(None)),
        "?system_error": (bool, str, type(None)),
    },
    "cancel_task": {"task_id": bytes},
    "cancel_local": {"task_id": bytes},
    "task_event": {"events": list},
    "task_counts": {"?finished": int, "?failed": int},
    "span_event": {"spans": list},
    "list_spans": {"?limit": int},
    # actors
    "create_actor": {"spec": dict},
    "submit_actor_task": {"spec": dict},
    "actor_task": {"spec": dict},
    "actor_created": {
        "actor_id": bytes, "node_id": bytes, "?failed": bool,
    },
    "actor_worker_died": {"actor_id": bytes, "?creating": bool},
    "kill_actor": {"actor_id": bytes, "?no_restart": bool},
    "kill_actor_local": {"actor_id": bytes},
    "get_named_actor": {"name": str, "?namespace": str},
    "get_actor_info": {"actor_id": bytes},
    # placement groups
    "create_placement_group": {
        "pg_id": bytes, "bundles": list, "strategy": str,
        "?name": (str, type(None)),
    },
    "placement_group_state": {"pg_id": bytes},
    "placement_group_table": {},
    "remove_placement_group": {"pg_id": bytes},
    "prepare_bundle": {
        "pg_id": bytes, "bundle_index": int, "resources": dict,
    },
    "commit_bundle": {"pg_id": bytes, "bundle_index": int},
    "release_bundle": {"pg_id": bytes, "?bundle_index": int},
    # cluster state / observability
    "cluster_resources": {},
    "available_resources": {},
    "state_summary": {},
    "list_task_events": {"?limit": int},
    "list_nodes": {},
    "list_actors": {},
    "list_objects": {"?limit": int},
    "cluster_load": {},
    "request_resources": {"bundles": list},
    "metrics_record": {
        "records": list,
        "?sender": (str, type(None)),
        "?seq": (int, type(None)),
    },
    "metrics_summary": {},
    "metrics_timeseries": {
        "?name": (str, type(None)),
        "?since": _num,
        "?limit": int,
    },
    "event_stats": {},
    # flight recorder / doctor (rings are pulled, never pushed)
    "flight_recorder": {
        "?limit": int, "?kinds": (list, type(None)),
        "?pid": int, "?node_id": (bytes, type(None)),
    },
    "inspect": {},
    "worker_inspect": {"?node_id": (bytes, type(None))},
    "step_summary": {"?limit": int, "?records": bool},
    "diagnose": {
        "?hung_task_s": _num, "?straggler_threshold": _num,
        "?capture_stacks": bool, "?limit": int,
    },
    # pubsub / log streaming
    "subscribe_logs": {"?channels": list},
    "unsubscribe_logs": {},
    "log_batch": {"batches": list, "node": str},
    "publish_event": {"channel": str, "payload": dict},
}


def has_schema(method: str) -> bool:
    """Whether `method` has a registered argument schema. Dispatch
    (rpc.RpcServer._dispatch) warns once per process for methods
    served without one — schema-less dispatch skips typed validation,
    which is exactly the drift `ray_tpu check` (RT104) exists to
    catch."""
    return method in SCHEMAS


def validate(method: str, msg: Dict[str, Any]) -> Optional[str]:
    """Check `msg` against the method's schema. Returns an error
    string, or None when valid. Methods without a registered schema
    pass (the completeness test keeps the registry in sync)."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return None
    for name, types in schema.items():
        optional = name.startswith("?")
        field = name[1:] if optional else name
        if field not in msg:
            if optional:
                continue
            return f"{method}: missing required field {field!r}"
        value = msg[field]
        if not isinstance(value, types):
            expected = (
                types.__name__
                if isinstance(types, type)
                else "/".join(t.__name__ for t in types)
            )
            return (
                f"{method}: field {field!r} expects {expected}, got "
                f"{type(value).__name__}"
            )
    return None
