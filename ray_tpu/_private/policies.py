"""Cluster scheduling policies.

Reimplements the reference's pluggable node-selection policies
(reference: src/ray/raylet/scheduling/policy/ — hybrid policy
hybrid_scheduling_policy.h:14-40 packs onto the local node up to a
utilization threshold then spreads; spread_scheduling_policy.cc
round-robins; node_affinity_scheduling_policy.cc pins to a node with a
soft fallback; node_label_scheduling_policy.cc matches label
expressions). Placement here is centralized on the head daemon, which
holds the cluster load view refreshed by heartbeats — functionally the
path a task takes through GCS-based scheduling rather than raylet
spillback.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .scheduler import ResourceSet


@dataclass
class NodeView:
    """Head-side snapshot of one node used for placement decisions."""

    node_id: bytes
    total: ResourceSet
    available: ResourceSet
    labels: Dict[str, str] = field(default_factory=dict)
    is_local: bool = False  # the head node itself


def _utilization(node: NodeView) -> float:
    total = node.total.to_dict()
    avail = node.available.to_dict()
    worst = 0.0
    for name, cap in total.items():
        if cap <= 0:
            continue
        used = cap - avail.get(name, 0.0)
        worst = max(worst, used / cap)
    return worst


def _feasible(nodes: List[NodeView], request: ResourceSet) -> List[NodeView]:
    return [n for n in nodes if request.fits_in(n.total)]


def _label_match(node: NodeView, expr: Dict[str, list]) -> bool:
    # expr: {key: [allowed values]}; empty list means "key exists".
    for key, allowed in expr.items():
        value = node.labels.get(key)
        if value is None:
            return False
        if allowed and value not in allowed:
            return False
    return True


class PlacementPolicy:
    """Stateful picker: round-robin memory for SPREAD lives here."""

    def __init__(self, spread_threshold: float = 0.5, top_k_frac: float = 0.2):
        self._spread_threshold = spread_threshold
        self._top_k_frac = top_k_frac
        self._spread_index = 0

    def pick(
        self,
        nodes: List[NodeView],
        request: ResourceSet,
        strategy: Optional[dict] = None,
    ) -> Optional[bytes]:
        """Return the chosen node_id, or None if no feasible node exists
        (the task is infeasible until the cluster changes)."""
        strategy = strategy or {"type": "DEFAULT"}
        kind = strategy.get("type", "DEFAULT")
        if kind == "NODE_AFFINITY":
            target = strategy["node_id"]
            if isinstance(target, str):
                target = bytes.fromhex(target)
            for n in nodes:
                if n.node_id == target and request.fits_in(n.total):
                    return target
            if strategy.get("soft"):
                return self._hybrid(nodes, request)
            return None
        if kind == "NODE_LABEL":
            hard = strategy.get("hard") or {}
            soft = strategy.get("soft") or {}
            matching = [n for n in nodes if _label_match(n, hard)]
            preferred = [n for n in matching if _label_match(n, soft)]
            return self._hybrid(preferred or matching, request)
        if kind == "SPREAD":
            return self._spread(nodes, request)
        return self._hybrid(nodes, request)

    def _spread(
        self, nodes: List[NodeView], request: ResourceSet
    ) -> Optional[bytes]:
        feasible = _feasible(nodes, request)
        if not feasible:
            return None
        feasible.sort(key=lambda n: n.node_id)
        # Prefer nodes that can run it now, keeping round-robin order.
        for offset in range(len(feasible)):
            node = feasible[(self._spread_index + offset) % len(feasible)]
            if request.fits_in(node.available):
                self._spread_index = (
                    self._spread_index + offset + 1
                ) % len(feasible)
                return node.node_id
        node = feasible[self._spread_index % len(feasible)]
        self._spread_index = (self._spread_index + 1) % len(feasible)
        return node.node_id

    def _hybrid(
        self, nodes: List[NodeView], request: ResourceSet
    ) -> Optional[bytes]:
        """Local-first up to the utilization threshold, then best-fit
        across the cluster; ties broken randomly over the top-k least
        utilized (reference: HybridSchedulingPolicy)."""
        feasible = _feasible(nodes, request)
        if not feasible:
            return None
        local = next((n for n in feasible if n.is_local), None)
        if (
            local is not None
            and request.fits_in(local.available)
            and _utilization(local) <= self._spread_threshold
        ):
            return local.node_id
        runnable = [n for n in feasible if request.fits_in(n.available)]
        pool = runnable or feasible
        pool = sorted(pool, key=_utilization)
        k = max(1, int(len(pool) * self._top_k_frac))
        return random.choice(pool[:k]).node_id
