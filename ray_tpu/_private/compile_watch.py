"""XLA-layer compile watcher (runtime core).

The framework can attribute every millisecond of a step to
data_wait/h2d/send/recv/queue stalls — but the layer that actually
burns the TPU, XLA, was a black box: a silent recompile storm (the
classic JAX perf killer: one drifting shape re-tracing the train step
or engine decode every iteration) showed up only as mysteriously slow
steps. This module is the per-process listener that turns compiles
into first-class observability:

* ``instrument(name, fn)`` wraps a jitted callable. The hot path is a
  digest of the call's arg shapes/dtypes checked against the shapes
  already seen — a tuple build + one set lookup, microseconds against
  a multi-ms step (the <1%-of-step bar is enforced by a unit test).
  A digest MISS means XLA is about to trace+compile: the call is
  timed, ``jax.monitoring`` event-duration hooks (registered lazily;
  available on jax 0.4.x) attribute the exact backend-compile seconds
  to the active program, and the compilation is recorded as
  (program name, shape digest, duration).
* Every recorded compile (a) bills ``compile_ms`` as a first-class
  stall phase into `step_telemetry` — cold-compile steps stop
  polluting steady-state goodput, exactly like data_wait/h2d; (b)
  exports ``rt_jax_compiles_total`` / ``rt_jax_compile_ms`` through
  the metrics pipe with the PROGRAM NAME as the only label (shape
  digests stay in the bounded diagnostic ring — RT010's
  bounded-cardinality rule holds by construction); (c) ships a
  ``kind="compile"`` record to the head, whose per-program digest
  ring makes a storm *diagnosable*: same program, ``>=
  compile_storm_threshold`` distinct shape digests -> `doctor`
  ``verdict.compile`` names the program, the compile count, and the
  differing shape dimension.
* ``device_memory()`` is the HBM side: per-process bytes-in-use/peak
  from ``device.memory_stats()`` on accelerator backends, ``None`` on
  CPU (degrade to ABSENT, never fake zeros) — `step_telemetry` folds
  it into every step record.

Digest semantics: array-typed leaves digest as (dtype, shape) — the
pair XLA keys its executable cache on. Python numeric scalars digest
as their TYPE only (jit weak-types them; digesting values would mint
a fake storm out of a healthy traced scalar), so a static-argnum
value change is undercounted rather than ever over-reported. Lives in
_private so the data/telemetry layers can import it without dragging
in jax; nothing here imports jax at module import time.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "instrument",
    "record_compile",
    "fold_record",
    "snapshot",
    "detect_storms",
    "shape_delta",
    "device_memory",
    "configure",
    "enabled",
    "storm_threshold",
    "reset",
    "load_inventory",
    "static_hint",
    "WatchedFunction",
]

#: Distinct shape digests retained per program (diagnostic ring; the
#: storm threshold must stay below this or a storm could never be
#: proven).
DIGEST_RING = 32

#: Only digests seen within this window count toward a storm: a
#: cluster's lifetime legitimately accumulates distinct shapes
#: (warmup buckets, redeploys, successive jobs) — a storm is many
#: distinct shapes RECENTLY, and this window is what lets a healthy
#: long-lived cluster's doctor go back to exit 0 once the drifting
#: loop stops.
STORM_WINDOW_S = 600.0

#: Cap on one WatchedFunction's seen-digest set. Under the very
#: storm the watcher detects, a drifting shape mints one digest per
#: iteration — without a cap the hot-path set (full treedef+leaf
#: tuples) grows for days. Clearing on overflow costs re-misses for
#: known shapes, which re-record only if XLA actually compiles.
SEEN_CAP = 4096

#: rt_jax_compile_ms histogram boundaries (ms): sub-ms cache re-hits
#: through minutes-long TPU compiles.
COMPILE_MS_BOUNDARIES = (
    1.0, 10.0, 50.0, 100.0, 500.0, 1000.0, 5000.0, 15000.0, 60000.0,
)

_lock = threading.Lock()  # rt: noqa[RT004] — held for dict ops only, never across a fork point
#: program name -> {"compiles", "total_ms", "digests": OrderedDict}
#: — the same structure the head daemon folds wire records into
#: (`fold_record`), so `detect_storms` serves both sides.
_programs: Dict[str, dict] = {}
_tl = threading.local()
#: Process-global mirror of the per-thread frame stacks: jax's
#: monitoring listener can fire from a different thread than the
#: caller (observed with cpp_pjit dispatch), where the thread-local
#: stack is empty — the global LIFO is the fallback that still
#: credits the (rare, effectively serialized) in-flight compile.
_global_stack: List[list] = []
_monitoring_installed = False
#: Set the first time a backend_compile monitoring event ACTUALLY
#: fires in this process — the proof that exact attribution works on
#: this jax. Until then, durations fall back to wall clock.
_monitoring_seen = False


def _env_enabled() -> bool:
    raw = os.environ.get("RT_compile_watch_enabled")
    if raw is None:
        return True
    return raw.lower() in ("1", "true", "yes")


_enabled = _env_enabled()
_storm_threshold = 8


def configure(config) -> None:
    """Apply the cluster config. The env var stays the documented
    per-process kill switch (same contract as the flight recorder):
    registration must not re-enable a watcher this process's
    environment disabled."""
    global _enabled, _storm_threshold
    _enabled = _env_enabled() and bool(
        getattr(config, "compile_watch_enabled", True)
    )
    _storm_threshold = int(
        getattr(config, "compile_storm_threshold", _storm_threshold)
    )


def enabled() -> bool:
    return _enabled


def storm_threshold() -> int:
    return _storm_threshold


def reset() -> None:
    """Drop all recorded programs (tests)."""
    with _lock:
        _programs.clear()


# ---------------------------------------------------------------------
# arg digests
# ---------------------------------------------------------------------


def _sig(x: Any, depth: int = 0) -> tuple:
    """Structural signature of one argument: array leaves become
    ("A", dtype, shape) — exactly what XLA's executable cache keys on
    — containers recurse, numeric scalars keep only their type (see
    module docstring), strings keep their value (always jit
    statics)."""
    if depth > 6:
        return ("...",)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("A", str(dtype), tuple(int(d) for d in shape))
    if x is None or isinstance(x, (bool, int, float, complex)):
        return ("S", type(x).__name__)
    if isinstance(x, str):
        return ("C", x)
    if isinstance(x, (tuple, list)):
        return tuple(_sig(v, depth + 1) for v in x)
    if isinstance(x, dict):
        return (
            "D",
            tuple(
                (str(k), _sig(v, depth + 1))
                for k, v in sorted(x.items(), key=lambda kv: str(kv[0]))
            ),
        )
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return (
            "O",
            type(x).__name__,
            tuple(
                (f.name, _sig(getattr(x, f.name), depth + 1))
                for f in dataclasses.fields(x)
            ),
        )
    return ("T", type(x).__name__)


_tree_flatten = None


def _get_tree_flatten():
    """jax.tree_util.tree_flatten when jax is already loaded (the
    C-implemented flatten is ~20x the pure-Python walk on a
    100-leaf param tree); never the import that drags jax in."""
    global _tree_flatten
    if _tree_flatten is None and "jax" in sys.modules:
        try:
            from jax.tree_util import tree_flatten

            _tree_flatten = tree_flatten
        except Exception:  # noqa: BLE001 — fallback walk below
            _tree_flatten = False
    return _tree_flatten or None


def arg_digest(args: tuple, kwargs: dict) -> tuple:
    """Hashable digest of a call's shape/dtype structure — the hot
    path of every instrumented call (the <1%-of-step bar lives
    here). Fast path: one C tree_flatten + a per-leaf
    (dtype, shape) pair; array leaves keep dtype OBJECTS (interned,
    hashable, repr-stable) instead of strings. Falls back to the
    pure-Python structural walk when jax isn't loaded or the tree
    has unflattenable parts."""
    flatten = _get_tree_flatten()
    if flatten is not None:
        try:
            flat, treedef = flatten(
                (args, kwargs) if kwargs else args
            )
            leaves = []
            append = leaves.append
            for x in flat:
                dtype = getattr(x, "dtype", None)
                if dtype is not None:
                    append((dtype, tuple(x.shape)))
                elif isinstance(x, str):
                    append(("str", x))
                else:
                    # Scalars by TYPE only (jit weak-types them);
                    # unregistered objects likewise — undercount,
                    # never a fake storm.
                    append((type(x).__name__, None))
            return (treedef, tuple(leaves))
        except Exception:  # noqa: BLE001 — unflattenable tree
            pass
    if kwargs:
        return (
            _sig(args),
            tuple((k, _sig(v)) for k, v in sorted(kwargs.items())),
        )
    return (_sig(args),)


def _array_leaves(sig: Any, out: List[tuple]) -> None:
    if isinstance(sig, tuple):
        if len(sig) == 3 and sig[0] == "A":
            out.append((sig[1], sig[2]))
            return
        for part in sig:
            _array_leaves(part, out)


def digest_leaves(digest: Any) -> List[tuple]:
    """The (dtype, shape) array leaves of a digest, in call order —
    what `shape_delta` diffs and the wire ships. Handles both digest
    formats: fast-path ``(treedef, leaf_pairs)`` — told apart by its
    non-tuple treedef head — and the structural-walk fallback."""
    leaves: List[tuple] = []
    if (
        isinstance(digest, tuple)
        and len(digest) == 2
        and not isinstance(digest[0], tuple)
        and isinstance(digest[1], tuple)
    ):
        for leaf in digest[1]:
            # Array leaves are the (dtype, shape-tuple) pairs;
            # ("str", s) / (typename, None) carry no shape.
            if isinstance(leaf[1], tuple):
                leaves.append((str(leaf[0]), leaf[1]))
        return leaves
    _array_leaves(digest, leaves)
    return leaves


_DTYPE_SHORT = {
    "float32": "f32", "float16": "f16", "bfloat16": "bf16",
    "float64": "f64", "int32": "i32", "int64": "i64", "int8": "i8",
    "uint8": "u8", "bool": "b1",
}


def _leaf_repr(leaf: tuple) -> str:
    dtype, shape = leaf
    short = _DTYPE_SHORT.get(str(dtype), str(dtype))
    return f"{short}[{','.join(str(d) for d in shape)}]"


def shapes_repr(leaves) -> str:
    """Compact human rendering of a digest's array leaves, e.g.
    ``i32[1,32] f32[8,256]`` (bounded: first 8 leaves + a count)."""
    leaves = list(leaves)
    head = " ".join(_leaf_repr(leaf) for leaf in leaves[:8])
    if len(leaves) > 8:
        head += f" +{len(leaves) - 8} more"
    return head


def digest_key(digest: Any) -> str:
    """Deterministic short key for a digest — stable ACROSS processes
    (`hash()` is salted per interpreter), so the head's distinct-shape
    count doesn't inflate when eight ranks compile the same shape."""
    return hashlib.sha1(repr(digest).encode()).hexdigest()[:12]


def shape_delta(prev_leaves, new_leaves) -> str:
    """Name WHAT drifted between two compiles of one program: the
    first array leaf whose shape/dtype differs, down to the
    dimension — the 'find the drifting shape' half of the recompile
    runbook. Indices are FLATTENED array-leaf positions in call
    order (a nested param tree contributes many leaves before the
    batch arrays), so the message says "array leaf", never "arg"."""
    prev_leaves, new_leaves = list(prev_leaves), list(new_leaves)
    prev_leaves = [tuple(leaf) if not isinstance(leaf, tuple) else leaf
                   for leaf in prev_leaves]
    new_leaves = [tuple(leaf) if not isinstance(leaf, tuple) else leaf
                  for leaf in new_leaves]
    if len(prev_leaves) != len(new_leaves):
        return (
            f"array-leaf arity changed: {len(prev_leaves)} -> "
            f"{len(new_leaves)} array leaves"
        )
    for i, (a, b) in enumerate(zip(prev_leaves, new_leaves)):
        a = (a[0], tuple(a[1]))
        b = (b[0], tuple(b[1]))
        if a == b:
            continue
        if a[0] != b[0]:
            return (
                f"array leaf {i}: dtype "
                f"{_leaf_repr(a)} -> {_leaf_repr(b)}"
            )
        dims = [
            d for d, (x, y) in enumerate(zip(a[1], b[1])) if x != y
        ] or ["rank"]
        return (
            f"array leaf {i}: {_leaf_repr(a)} -> {_leaf_repr(b)} "
            f"(dim {dims[0]} drifting)"
        )
    return "shapes identical (static-arg or donation change)"


# ---------------------------------------------------------------------
# the program table (shared shape: local registry AND head fold)
# ---------------------------------------------------------------------


def fold_record(
    programs: Dict[str, dict],
    program: str,
    duration_ms: float,
    info: Optional[dict] = None,
    ring: int = DIGEST_RING,
) -> None:
    """Fold one compile event into a program table. Used by the local
    registry below and by the head daemon on ``kind="compile"`` wire
    records — one structure, one storm detector. Caller owns
    locking."""
    info = info or {}
    row = programs.setdefault(
        program,
        {"compiles": 0, "total_ms": 0.0, "digests": OrderedDict()},
    )
    row["compiles"] += 1
    row["total_ms"] += float(duration_ms)
    key = info.get("digest")
    if not key:
        return
    digests = row["digests"]
    entry = digests.get(key)
    if entry is not None:
        entry["count"] += 1
        entry["ms"] = float(duration_ms)
        entry["time"] = float(info.get("time", time.time()))
        digests.move_to_end(key)
        return
    while len(digests) >= ring:
        digests.popitem(last=False)
    digests[key] = {
        "count": 1,
        "ms": round(float(duration_ms), 3),
        "time": float(info.get("time", time.time())),
        "shapes": str(info.get("shapes", "")),
        "leaves": tuple(
            tuple(leaf) for leaf in info.get("leaves", ())
        ),
    }


def detect_storms(
    programs: Dict[str, dict],
    threshold: Optional[int] = None,
    window_s: float = STORM_WINDOW_S,
) -> List[dict]:
    """Recompile-storm findings over a program table: same program
    name, >= threshold distinct shape digests seen within
    `window_s`. A healthy program with a bounded bucket family
    (prefill length buckets, policy batch buckets) mints its digests
    once at warmup and they AGE OUT of the window; a drifting shape
    mints a new digest every iteration and holds the count above
    threshold for as long as the storm runs."""
    threshold = _storm_threshold if threshold is None else int(threshold)
    now = time.time()
    storms: List[dict] = []
    for name in sorted(programs):
        row = programs[name]
        digests = row.get("digests") or {}
        keys = [
            k
            for k, entry in digests.items()
            if float(entry.get("time", now)) >= now - window_s
        ]
        if len(keys) < max(2, threshold):
            continue
        delta = shape_delta(
            digests[keys[-2]].get("leaves", ()),
            digests[keys[-1]].get("leaves", ()),
        )
        last = digests[keys[-1]]
        storms.append(
            {
                "program": name,
                "compiles": row["compiles"],
                "distinct_shapes": len(keys),
                "total_ms": round(row["total_ms"], 1),
                "last_shapes": last.get("shapes", ""),
                "delta": delta,
                "detail": (
                    f"program {name!r} compiled {row['compiles']}x "
                    f"over {len(keys)} recent distinct arg-shape "
                    f"sets ({row['total_ms']:.0f} ms total) — "
                    f"{delta}"
                ),
            }
        )
    return storms


def snapshot() -> Dict[str, dict]:
    """This process's per-program compile table (counts, total ms,
    digest ring) — the local half of ``verdict.compile``; the head
    serves the cluster-folded equivalent."""
    with _lock:
        out: Dict[str, dict] = {}
        for name, row in _programs.items():
            out[name] = {
                "compiles": row["compiles"],
                "total_ms": round(row["total_ms"], 3),
                "distinct_shapes": len(row["digests"]),
                "digests": {
                    k: dict(v) for k, v in row["digests"].items()
                },
            }
        return out


# ---------------------------------------------------------------------
# jax.monitoring attribution
# ---------------------------------------------------------------------


def _active_stack() -> list:
    stack = getattr(_tl, "stack", None)
    if stack is None:
        stack = _tl.stack = []
    return stack


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    # Only backend_compile carries the cost worth attributing; the
    # trace/lowering events are sub-ms noise next to it.
    if not event.endswith("backend_compile_duration"):
        return
    global _monitoring_seen
    _monitoring_seen = True
    stack = getattr(_tl, "stack", None)
    if stack:
        # A registered program is mid-call on this thread: credit it.
        stack[-1][1] += float(duration)
        return
    # Listener fired off the caller's thread: credit the most recent
    # in-flight instrumented call instead.
    with _lock:
        if _global_stack:
            _global_stack[-1][1] += float(duration)
            return
    # A compile outside any instrumented program — still counted, so
    # "every compilation is recorded" holds; no digest, so it can
    # never fake a storm.
    record_compile(
        "(unregistered)", None, float(duration) * 1e3
    )


def _install_monitoring() -> None:
    """Register the jax.monitoring event-duration listener once per
    process. Lazy and gated on jax ALREADY being imported: the watcher
    must never be the thing that drags jax into a process."""
    global _monitoring_installed
    if _monitoring_installed or "jax" not in sys.modules:
        return
    with _lock:
        if _monitoring_installed:
            return
        try:
            from jax import monitoring as _monitoring

            _monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            _monitoring_installed = True
        except Exception:
            # Old/odd jax: the wall-clock fallback below still works.
            _monitoring_installed = True


# ---------------------------------------------------------------------
# recording + instrumentation
# ---------------------------------------------------------------------


def record_compile(
    program: str,
    digest: Any,
    duration_ms: float,
    *,
    wall_ms: Optional[float] = None,
) -> None:
    """Record one compilation: local ring, ``compile_ms`` stall
    phase, and the metrics pipe (counter + histogram labeled by
    program NAME only; digest/shape detail rides the kind="compile"
    record into the head's bounded diagnostic ring, never a metric
    label)."""
    if not _enabled:
        return
    now = time.time()
    leaves = digest_leaves(digest) if digest is not None else []
    info = {
        "digest": digest_key(digest) if digest is not None else "",
        "shapes": shapes_repr(leaves) if leaves else "",
        "leaves": leaves,
        "time": now,
    }
    with _lock:
        fold_record(_programs, program, duration_ms, info)
    # Cold-compile time is a stall the loop paid, exactly like
    # data_wait: bill it so the compiling step's residual step_ms
    # stays honest and goodput classifies it as stall, not compute.
    from .step_telemetry import add_phase

    add_phase("compile_ms", float(duration_ms))
    try:
        from ..util.metrics import _Buffer

        tags = (("program", str(program)),)
        buf = _Buffer.get()
        buf.push(
            ("counter", "rt_jax_compiles_total", 1.0, tags)
        )
        buf.push(
            (
                "histogram",
                "rt_jax_compile_ms",
                float(duration_ms),
                tags,
                COMPILE_MS_BOUNDARIES,
            )
        )
        buf.push(
            (
                "compile",
                str(program),
                float(duration_ms),
                tuple(
                    sorted(
                        {
                            "pid": os.getpid(),
                            "digest": info["digest"],
                            "shapes": info["shapes"],
                            "leaves": tuple(
                                tuple(leaf) for leaf in leaves
                            ),
                            "wall_ms": round(
                                float(
                                    wall_ms
                                    if wall_ms is not None
                                    else duration_ms
                                ),
                                3,
                            ),
                        }.items()
                    )
                ),
            )
        )
    except Exception:  # noqa: BLE001 — observability never raises
        pass


class WatchedFunction:
    """An instrumented jitted callable. Hot path (shapes already
    seen): digest + one set lookup, then straight through. Miss path:
    the call runs inside a thread-local program frame so the
    monitoring listener attributes its backend-compile seconds here;
    wall time is the fallback duration when no monitoring event fired
    (old jax, or a cache hit we mistook for a miss — recorded
    honestly as near-zero)."""

    __slots__ = ("name", "_fn", "_seen", "_seen_lock")

    def __init__(self, name: str, fn: Callable):
        self.name = str(name)
        self._fn = fn
        self._seen: set = set()
        self._seen_lock = threading.Lock()
        _install_monitoring()

    def __call__(self, *args, **kwargs):
        if not _enabled:
            return self._fn(*args, **kwargs)
        digest = arg_digest(args, kwargs)
        with self._seen_lock:
            hit = digest in self._seen
        if hit:
            return self._fn(*args, **kwargs)
        stack = _active_stack()
        frame = [self.name, 0.0]
        stack.append(frame)
        with _lock:
            _global_stack.append(frame)
        t0 = time.perf_counter()
        try:
            out = self._fn(*args, **kwargs)
        finally:
            stack.pop()
            with _lock:
                # Remove THIS frame (identity), wherever it sits:
                # concurrent compiling threads pop out of LIFO order.
                for i in range(len(_global_stack) - 1, -1, -1):
                    if _global_stack[i] is frame:
                        del _global_stack[i]
                        break
        wall_ms = (time.perf_counter() - t0) * 1e3
        compiled_ms = frame[1] * 1e3
        with self._seen_lock:
            if len(self._seen) >= SEEN_CAP:
                self._seen.clear()
            self._seen.add(digest)
        if compiled_ms > 0.0:
            # Exact backend-compile seconds attributed by the
            # monitoring listener.
            record_compile(
                self.name, digest, compiled_ms, wall_ms=wall_ms
            )
        elif not _monitoring_seen:
            # No listener evidence on this jax yet: wall clock is
            # the honest fallback (documented imprecision — it
            # includes the call's execution).
            record_compile(
                self.name, digest, wall_ms, wall_ms=wall_ms
            )
        # else: monitoring demonstrably works in this process and no
        # compile event fired — XLA's own cache absorbed the miss
        # (e.g. a re-wrapped program whose jit already compiled this
        # shape). Recording the call's wall time would bill plain
        # EXECUTION as compile_ms and mint a phantom compile count;
        # the digest is marked seen and nothing is recorded.
        return out

    def stats(self) -> Dict[str, Any]:
        """This program's compile counts from the process registry
        (the `engine_stats` surface: a mid-traffic recompile is an
        engine bug — now a visible counter)."""
        with _lock:
            row = _programs.get(self.name)
            if row is None:
                return {"compiles": 0, "distinct_shapes": 0}
            return {
                "compiles": row["compiles"],
                "distinct_shapes": len(row["digests"]),
            }


def instrument(name: str, fn: Callable) -> WatchedFunction:
    """Register a jitted program with the compile watcher by NAME and
    return the wrapped callable. Names must be bounded-cardinality
    (program families, not per-request ids): they become the only
    label on the exported compile series."""
    return WatchedFunction(name, fn)


# ---------------------------------------------------------------------
# device memory (HBM) telemetry
# ---------------------------------------------------------------------


def device_memory() -> Optional[Dict[str, int]]:
    """Aggregate HBM stats of this process's local accelerator
    devices via ``device.memory_stats()``. Returns None when jax is
    not loaded, on CPU backends, or when the runtime exposes no
    stats — callers must treat None as ABSENT (no fields), never as
    zero: a fake 0/NaN would read as 'no pressure' on exactly the
    rank being diagnosed."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — probing must never raise
        return None
    in_use = peak = limit = 0
    seen = False
    for device in devices:
        if getattr(device, "platform", "cpu") == "cpu":
            continue
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if not stats:
            continue
        if "bytes_in_use" in stats:
            in_use += int(stats["bytes_in_use"])
            seen = True
        peak += int(
            stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
        )
        limit += int(stats.get("bytes_limit", 0))
    if not seen:
        return None
    out = {"hbm_bytes_in_use": in_use, "hbm_peak_bytes": peak}
    if limit > 0:
        out["hbm_bytes_limit"] = limit
    return out


# ---------------------------------------------------------------------
# static inventory bridge (devtools/accel.py <-> verdict.compile)
# ---------------------------------------------------------------------

#: Cached program inventory (or False after a failed load, so a
#: broken environment probes the filesystem exactly once).
_inventory: Any = None


def load_inventory(path: Optional[str] = None, *, refresh: bool = False):
    """The static half of the bridge: the program inventory produced
    by ``ray_tpu devtools accel --inventory`` (every jit/shard_map
    wrap site, its registered program name, and its RT302
    recompile-hazard sites). Resolution order: explicit `path` arg ->
    ``RT_accel_inventory`` env var (a JSON file, for clusters whose CI
    exports the inventory as an artifact) -> a lazy in-process scan of
    the installed package. Returns the inventory dict or None;
    failures are cached so the doctor path never pays the scan twice."""
    global _inventory
    if refresh:
        _inventory = None
    if _inventory is not None:
        return _inventory or None
    src = path or os.environ.get("RT_accel_inventory")
    try:
        if src:
            import json

            with open(src) as f:
                _inventory = json.load(f)
        else:
            from ray_tpu.devtools.accel import build_inventory

            pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            _inventory = build_inventory([pkg])
    except Exception:  # noqa: BLE001 — a hint source must never break diagnose
        _inventory = False
        return None
    return _inventory or None


def static_hint(program: str) -> Optional[str]:
    """Resolve a live program name (as seen in a recompile storm) to
    its static wrap site and any RT302 hazards the analyzer proved
    there. Literal inventory names match exactly; f-string program
    names were inventoried as fnmatch patterns (``engine.run[*]``).
    Returns a one-line human hint or None when the bridge has nothing
    — absence of a hint must read as 'unknown', not 'clean'."""
    inv = load_inventory()
    if not inv:
        return None
    import re

    def _pattern_matches(pattern: str, name: str) -> bool:
        # Program names legitimately contain fnmatch metacharacters
        # (`engine.run[gen3]`), so only `*` is a wildcard — everything
        # else matches literally.
        parts = (re.escape(p) for p in pattern.split("*"))
        return re.fullmatch(".*".join(parts), name) is not None

    match = None
    for rec in inv.get("programs", ()):
        name = rec.get("program")
        if not name:
            continue
        if rec.get("name_kind") == "literal":
            if name == program:
                match = rec
                break
        elif _pattern_matches(name, program) and match is None:
            match = rec
    if match is None:
        return None
    site = f"{match['path']}:{match['line']}"
    hazards = match.get("hazards") or []
    if hazards:
        spots = "; ".join(
            f"{h['path']}:{h['line']} {h['message']}" for h in hazards
        )
        return (
            f"static analysis flagged this program (RT302): {spots} "
            f"[wrap at {site}]"
        )
    return (
        f"wrap site {site} has no static RT302 hazard on record — "
        f"suspect call-site shape drift; run "
        f"`ray_tpu devtools accel` after reproducing"
    )
