"""Typed binary identifiers for the runtime.

Design follows the reference's ID specification (reference:
src/ray/common/id.h, src/ray/design_docs/id_specification.md): every
entity in the system gets a fixed-width binary ID; ObjectIDs embed the
ID of the task that created them plus a return-index so ownership and
lineage can be derived without a directory lookup.

Unlike the reference (C++ templates + 28-byte ObjectIDs), we keep a
small pure-Python implementation: IDs are immutable bytes wrappers with
cheap hashing, hex round-tripping, and deterministic derivation.
"""

from __future__ import annotations

import hashlib
import os
import struct

__all__ = [
    "BaseID",
    "JobID",
    "TaskID",
    "ActorID",
    "ObjectID",
    "NodeID",
    "WorkerID",
    "PlacementGroupID",
    "ClusterID",
]


class BaseID:
    """Immutable fixed-size binary identifier."""

    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash((type(self).__name__, self._bytes))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    # Pickle support (slots-based).
    def __getstate__(self):
        return self._bytes

    def __setstate__(self, state):
        self._bytes = state
        self._hash = hash((type(self).__name__, self._bytes))


class ClusterID(BaseID):
    SIZE = 16


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class JobID(BaseID):
    """4-byte job id (reference: src/ray/common/id.h JobID::Size() == 4)."""

    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    def int_value(self) -> int:
        return struct.unpack(">I", self._bytes)[0]


class ActorID(BaseID):
    """12-byte actor id: 8 random bytes + 4-byte job id suffix."""

    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:])


class TaskID(BaseID):
    """16-byte task id derived from (parent task, submission index).

    The derivation is deterministic so retries of the same submission
    produce the same TaskID, which is what makes lineage-based object
    reconstruction possible (reference: src/ray/common/id.h
    TaskID::ForNormalTask).
    """

    SIZE = 16

    #: (job_bytes, parent_bytes) -> prefix-fed hasher. A submit loop
    #: derives every task id from the SAME (job, parent) pair, so the
    #: prefix hash is computed once and copy()d per task — about half
    #: the sha256 cost on the 20k/s submit path. Bounded: one entry
    #: per submitting (job, parent) pair, pruned at 256.
    _prefix_cache: dict = {}

    @classmethod
    def for_task(
        cls, job_id: JobID, parent: "TaskID", submit_index: int
    ) -> "TaskID":
        key = (job_id._bytes, parent._bytes)
        base = cls._prefix_cache.get(key)
        if base is None:
            if len(cls._prefix_cache) >= 256:
                cls._prefix_cache.clear()
            base = hashlib.sha256(key[0] + key[1])
            cls._prefix_cache[key] = base
        h = base.copy()
        h.update(struct.pack(">Q", submit_index))
        return cls(h.digest()[: cls.SIZE])

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        h = hashlib.sha256()
        h.update(b"actor_creation")
        h.update(actor_id.binary())
        return cls(h.digest()[: cls.SIZE])

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        h = hashlib.sha256()
        h.update(b"driver")
        h.update(job_id.binary())
        return cls(h.digest()[: cls.SIZE])


class ObjectID(BaseID):
    """20-byte object id = 16-byte creating TaskID + 4-byte index.

    Index 0 is reserved for `put` objects counter space; task returns
    use indices starting at 1 (reference: src/ray/common/id.h
    ObjectID::FromIndex).
    """

    SIZE = 20

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid colliding
        # with return-object indices.
        return cls(task_id.binary() + struct.pack(">I", 0x80000000 | put_index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:16])

    def index(self) -> int:
        return struct.unpack(">I", self._bytes[16:])[0]


class PlacementGroupID(BaseID):
    SIZE = 16
