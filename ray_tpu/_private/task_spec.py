"""Task specification dicts + error payload helpers.

The reference's TaskSpecification is an immutable protobuf wrapper
(reference: src/ray/common/task/task_spec.h). Here a spec is a plain
dict built once at submit time and shipped over the socket:

    {
      "task_id": bytes, "job_id": bytes, "kind": "normal" |
      "actor_creation" | "actor_task", "name": str,
      "function_key": str,            # KV key of the pickled function
      "args": [("inline", bytes) | ("ref", oid_bytes)],
      "returns": [oid_bytes, ...],
      "resources": {"CPU": 1.0, ...},
      "max_retries": int,
      # actor fields
      "actor_id": bytes, "method": str, "handle_meta": {...},
    }

Error payloads are pickled dicts `{"kind", "detail", "traceback"}`;
`raise_from_payload` maps them back to typed exceptions at `get`
(reference: RayTaskError round-trip, python/ray/exceptions.py).
"""

from __future__ import annotations

import pickle
import traceback as _tb

from .. import exceptions as exc

_ERROR_TYPES = {
    "TaskError": exc.TaskError,
    "WorkerCrashedError": exc.WorkerCrashedError,
    "ActorDiedError": exc.ActorDiedError,
    "ActorUnavailableError": exc.ActorUnavailableError,
    "ObjectLostError": exc.ObjectLostError,
    "TaskCancelledError": exc.TaskCancelledError,
    "RuntimeEnvSetupError": exc.RuntimeEnvSetupError,
}


def make_error_payload(kind: str, detail: str, tb: str = "") -> bytes:
    return pickle.dumps({"kind": kind, "detail": detail, "traceback": tb})


def make_exception_payload(e: BaseException) -> bytes:
    """Payload for an application exception raised inside a task.

    The original exception object is pickled when possible so user
    `except SomeError:` clauses keep working across the process
    boundary; otherwise we fall back to its repr.
    """
    tb = "".join(_tb.format_exception(type(e), e, e.__traceback__))
    try:
        cause = pickle.dumps(e)
    except Exception:
        cause = None
    info = {
        "kind": "TaskError",
        "detail": repr(e),
        "traceback": tb,
        "cause": cause,
    }
    # Generator tasks annotate how many items were sealed before the
    # failure so consumers can drain them before seeing the error
    # (object_ref.ObjectRefGenerator mid-stream error protocol).
    emitted = getattr(e, "__rt_items_emitted__", None)
    if emitted is not None:
        info["items_emitted"] = emitted
    return pickle.dumps(info)


def raise_from_payload(payload: bytes) -> None:
    info = pickle.loads(payload)
    kind = info.get("kind", "TaskError")
    if kind == "TaskError":
        cause = info.get("cause")
        original = None
        if cause is not None:
            try:
                original = pickle.loads(cause)
            except Exception:
                original = None
        if isinstance(original, BaseException):
            # Re-raise the user's exception type so `except ValueError:`
            # works across the process boundary; the remote traceback
            # rides along as __cause__.
            raise original from exc.TaskError(
                info["detail"], info.get("traceback", "")
            )
        raise exc.TaskError(info["detail"], info.get("traceback", ""))
    error_cls = _ERROR_TYPES.get(kind, exc.RayTpuError)
    raise error_cls(f"{info.get('detail', '')}\n{info.get('traceback', '')}")
