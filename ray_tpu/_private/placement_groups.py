"""Placement-group bookkeeping and bundle placement.

Reference semantics: gcs_server/gcs_placement_group_manager.cc drives a
2PC prepare/commit of bundle resources against raylets
(raylet/placement_group_resource_manager.h); committed bundles surface
as formatted node resources `{R}_group_{pg}` / `{R}_group_{idx}_{pg}`
plus a `bundle_group_*` marker pool, and tasks scheduled into the group
have their resource requests rewritten to those names — so the ordinary
cluster scheduler handles placement-group affinity with no special
cases. Bundle-placement strategies per
raylet/scheduling/policy/bundle_scheduling_policy.cc: PACK / SPREAD /
STRICT_PACK / STRICT_SPREAD.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .policies import NodeView
from .scheduler import ResourceSet

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

# Marker-pool size per bundle; tasks gated on the group take 0.001 of
# it (reference: BundleSpecification::GetFormattedResources).
BUNDLE_POOL = 1000.0


@dataclass
class PGEntry:
    pg_id: bytes
    bundles: List[dict]
    strategy: str
    name: str
    state: str = "PENDING"  # PENDING|CREATED|RESCHEDULING|REMOVED
    bundle_nodes: List[Optional[bytes]] = field(default_factory=list)

    def __post_init__(self):
        if not self.bundle_nodes:
            self.bundle_nodes = [None] * len(self.bundles)

    def to_table_entry(self) -> dict:
        return {
            "placement_group_id": self.pg_id.hex(),
            "name": self.name,
            "strategy": self.strategy,
            "bundles": list(self.bundles),
            "state": self.state,
            "bundle_nodes": [
                n.hex() if n else None for n in self.bundle_nodes
            ],
        }


def group_resources(pg_hex: str, index: int, bundle: dict) -> dict:
    """Node resources created when a bundle commits."""
    out: Dict[str, float] = {}
    for name, amount in bundle.items():
        out[f"{name}_group_{pg_hex}"] = (
            out.get(f"{name}_group_{pg_hex}", 0.0) + amount
        )
        out[f"{name}_group_{index}_{pg_hex}"] = amount
    out[f"bundle_group_{pg_hex}"] = BUNDLE_POOL
    out[f"bundle_group_{index}_{pg_hex}"] = BUNDLE_POOL
    return out


def rewrite_request(resources: dict, pg_hex: str, index: int) -> dict:
    """Rewrite a task's resource request to target the group's
    formatted resources (wildcard when index < 0)."""
    out: Dict[str, float] = {}
    for name, amount in resources.items():
        if index >= 0:
            out[f"{name}_group_{index}_{pg_hex}"] = amount
        else:
            out[f"{name}_group_{pg_hex}"] = amount
    marker = (
        f"bundle_group_{index}_{pg_hex}"
        if index >= 0
        else f"bundle_group_{pg_hex}"
    )
    out[marker] = 0.001
    return out


class _SimNode:
    """Mutable available-view used while assigning bundles."""

    __slots__ = ("node_id", "available", "used")

    def __init__(self, view: NodeView):
        self.node_id = view.node_id
        self.available = view.available
        self.used = False

    def fits(self, request: ResourceSet) -> bool:
        return request.fits_in(self.available)

    def take(self, request: ResourceSet) -> None:
        self.available = self.available.subtract(request)
        self.used = True


def place_bundles(
    bundles: Sequence[dict],
    strategy: str,
    views: Sequence[NodeView],
    *,
    exclude: Sequence[bytes] = (),
) -> Optional[List[bytes]]:
    """Pick a node for every bundle; None if infeasible right now.

    `exclude` bars nodes from selection (used when rescheduling a
    STRICT_SPREAD group whose surviving bundles already occupy nodes).
    """
    sims = [
        _SimNode(v) for v in views if v.node_id not in set(exclude)
    ]
    requests = [ResourceSet(b) for b in bundles]
    if strategy == "STRICT_PACK":
        whole = ResourceSet()
        for r in requests:
            whole = whole.add(r)
        for sim in sims:
            if sim.fits(whole):
                return [sim.node_id] * len(bundles)
        return None
    if strategy == "STRICT_SPREAD":
        if len(sims) < len(bundles):
            return None
        return _assign_spread(requests, sims, strict=True)
    if strategy == "SPREAD":
        return _assign_spread(requests, sims, strict=False)
    return _assign_pack(requests, sims)


def _assign_pack(
    requests: List[ResourceSet], sims: List[_SimNode]
) -> Optional[List[bytes]]:
    """Greedy: keep filling nodes already holding bundles of this
    group before opening a new node (minimises node count)."""
    assignment: List[Optional[bytes]] = [None] * len(requests)
    for i, req in enumerate(requests):
        chosen = None
        for sim in sims:
            if sim.used and sim.fits(req):
                chosen = sim
                break
        if chosen is None:
            for sim in sims:
                if sim.fits(req):
                    chosen = sim
                    break
        if chosen is None:
            return None
        chosen.take(req)
        assignment[i] = chosen.node_id
    return assignment  # type: ignore[return-value]


def _assign_spread(
    requests: List[ResourceSet], sims: List[_SimNode], *, strict: bool
) -> Optional[List[bytes]]:
    """Distinct nodes first; soft spread falls back to reuse."""
    assignment: List[Optional[bytes]] = [None] * len(requests)
    for i, req in enumerate(requests):
        fresh = [s for s in sims if not s.used and s.fits(req)]
        if fresh:
            chosen = fresh[0]
        elif strict:
            return None
        else:
            reusable = [s for s in sims if s.fits(req)]
            if not reusable:
                return None
            chosen = reusable[0]
        chosen.take(req)
        assignment[i] = chosen.node_id
    return assignment  # type: ignore[return-value]
