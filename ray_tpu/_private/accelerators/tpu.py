"""TPU accelerator manager.

Reference: python/ray/_private/accelerators/tpu.py — chip detection via
/dev/accel* (:107), per-worker visibility via TPU_VISIBLE_CHIPS +
host-bounds env vars (:155-195), pod-type/worker-id from GCE metadata
or GKE env vars (:198-271), and the slice-scheduling auto-resources
`TPU-{pod_type}-head` + pod-name (:334-397) that make SPMD gang
scheduling expressible as ordinary resource requests.

TPU-first deviation: a TPU worker owns the host's *entire* chip set.
libtpu wants one process per chip-set, and SPMD programs address whole
hosts of a slice — so chips are not sub-divided across concurrent
workers the way GPUs are (SURVEY.md §7 hard part 1: "the worker pool
must pin TPU workers"). Sub-host granularity is expressed by starting
the node with explicit `num_tpus` instead.

Cloud metadata is read from env vars only (GCE metadata-server lookups
are gated out: zero-egress environments hang on them). The overrides
RT_TPU_* exist so tests and fake clusters can model pod topology.
"""

from __future__ import annotations

import glob
import os
import re
from functools import lru_cache
from typing import Dict, Optional, Tuple

from .base import AcceleratorManager

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"

# Generation -> chips per host (a v5e host has 4 or 8 chips; 4 is the
# common pod-slice shape; overridable via RT_TPU_CHIPS_PER_HOST).
_DEFAULT_CHIPS_PER_HOST = {
    "v2": 4,
    "v3": 4,
    "v4": 4,
    "v5e": 4,
    "v5p": 4,
    "v6e": 4,
}

_POD_TYPE_RE = re.compile(r"^(v\d+[a-z]*)-(\d+)$")


def _env(*names: str) -> Optional[str]:
    for name in names:
        value = os.environ.get(name)
        if value:
            return value
    return None


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    @lru_cache()
    def get_current_node_num_accelerators() -> int:
        override = os.environ.get("RT_TPU_CHIPS")
        if override is not None:
            return int(override)
        chips = glob.glob("/dev/accel*")
        if chips:
            return len(chips)
        try:
            entries = os.listdir("/dev/vfio")
        except FileNotFoundError:
            return 0
        return len([e for e in entries if e.isdigit()])

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """Pod type like 'v5e-16' (generation-chips across the slice)."""
        return _env("RT_TPU_POD_TYPE", "TPU_ACCELERATOR_TYPE")

    @staticmethod
    def get_current_node_tpu_name() -> Optional[str]:
        return _env("RT_TPU_NAME", "TPU_NAME")

    @staticmethod
    def get_current_node_tpu_worker_id() -> Optional[int]:
        raw = _env("RT_TPU_WORKER_ID", "TPU_WORKER_ID")
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    @staticmethod
    def is_valid_tpu_accelerator_type(pod_type: str) -> bool:
        return _POD_TYPE_RE.match(pod_type) is not None

    @staticmethod
    def get_extra_resources_and_labels(
        num_accelerators: int,
    ) -> Tuple[Dict[str, float], Dict[str, str]]:
        resources: Dict[str, float] = {}
        labels: Dict[str, str] = {}
        pod_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        pod_name = TPUAcceleratorManager.get_current_node_tpu_name()
        worker_id = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        if pod_type:
            labels["rt.io/tpu-pod-type"] = pod_type
            # Worker 0 of a slice advertises the head marker so one
            # task can claim the whole slice atomically (reference:
            # tpu.py:334 `TPU-{pod_type}-head`).
            if worker_id == 0 or worker_id is None:
                resources[f"TPU-{pod_type}-head"] = 1.0
        if pod_name:
            labels["rt.io/tpu-pod-name"] = pod_name
            # Every host of the slice carries the pod-name resource so
            # a STRICT_SPREAD placement group over it gang-reserves the
            # slice (reference: tpu.py:397).
            resources[pod_name] = 1.0
        if worker_id is not None:
            labels["rt.io/tpu-worker-id"] = str(worker_id)
        return resources, labels


def pod_type_num_chips(pod_type: str) -> int:
    """Total chips in a slice, from the pod type ('v5e-16' -> 16)."""
    m = _POD_TYPE_RE.match(pod_type)
    if not m:
        raise ValueError(f"bad TPU pod type {pod_type!r}")
    generation, count = m.group(1), int(m.group(2))
    # v2/v3 pod types count cores (2 per chip); v4+ count chips
    # (reference: tpu.py get_num_tpu_visible_chips_per_host).
    if generation in ("v2", "v3"):
        return count // 2
    return count


def chips_per_host(pod_type: str) -> int:
    override = os.environ.get("RT_TPU_CHIPS_PER_HOST")
    if override:
        return int(override)
    m = _POD_TYPE_RE.match(pod_type)
    generation = m.group(1) if m else "v5e"
    per_host = _DEFAULT_CHIPS_PER_HOST.get(generation, 4)
    return min(per_host, pod_type_num_chips(pod_type))


def pod_worker_count(pod_type: str) -> int:
    """Number of hosts in a slice."""
    total = pod_type_num_chips(pod_type)
    per_host = chips_per_host(pod_type)
    return max(1, (total + per_host - 1) // per_host)
