"""Accelerator manager interface (reference:
python/ray/_private/accelerators/accelerator.py — the abstract surface
every accelerator family implements)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class AcceleratorManager:
    """Detection + visibility scoping for one accelerator family."""

    @staticmethod
    def get_resource_name() -> str:
        raise NotImplementedError

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        raise NotImplementedError

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        raise NotImplementedError

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        return None

    @classmethod
    def get_current_process_visible_accelerator_ids(
        cls,
    ) -> Optional[List[str]]:
        import os

        raw = os.environ.get(cls.get_visible_accelerator_ids_env_var())
        if raw is None:
            return None
        if raw == "":
            return []
        return raw.split(",")

    @classmethod
    def set_visible_accelerator_ids(
        cls, env: Dict[str, str], ids: List[str]
    ) -> None:
        env[cls.get_visible_accelerator_ids_env_var()] = ",".join(ids)

    @staticmethod
    def get_extra_resources_and_labels(
        num_accelerators: int,
    ) -> Tuple[Dict[str, float], Dict[str, str]]:
        """Family-specific auto-resources (e.g. TPU pod head markers)
        and node labels."""
        return {}, {}
