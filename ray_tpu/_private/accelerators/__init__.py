"""Pluggable accelerator managers.

Reference: python/ray/_private/accelerators/ — one AcceleratorManager
per accelerator family, consulted at node start for resource detection
and at worker spawn for visibility scoping. TPU is the first-class
citizen here; the NVIDIA manager exists for CPU+GPU clusters driving
TPU pods from afar.
"""

from __future__ import annotations

from typing import Dict, Tuple

from .base import AcceleratorManager
from .nvidia_gpu import NvidiaGPUAcceleratorManager
from .tpu import TPUAcceleratorManager

_MANAGERS = {
    "TPU": TPUAcceleratorManager,
    "GPU": NvidiaGPUAcceleratorManager,
}


def get_accelerator_manager(resource_name: str) -> AcceleratorManager:
    try:
        return _MANAGERS[resource_name]()
    except KeyError:
        raise ValueError(
            f"no accelerator manager for resource {resource_name!r}"
        ) from None


def detect_accelerators(
    overrides: Dict[str, float] = None,
) -> Tuple[Dict[str, float], Dict[str, str]]:
    """Detect every accelerator on this host.

    Returns (resources, labels) to merge into the node's pool —
    including the TPU pod/gang resources used for slice-level
    scheduling (reference: _private/accelerators/tpu.py:334-397).
    `overrides` replaces detection per resource name; an override of 0
    hides the accelerator entirely (no count, no extra resources or
    labels).
    """
    overrides = overrides or {}
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    for manager_cls in _MANAGERS.values():
        manager = manager_cls()
        name = manager.get_resource_name()
        if name in overrides:
            count = overrides[name]
        else:
            count = manager.get_current_node_num_accelerators()
        if count <= 0:
            continue
        resources[name] = float(count)
        extra_res, extra_labels = manager.get_extra_resources_and_labels(
            count
        )
        resources.update(extra_res)
        labels.update(extra_labels)
    return resources, labels
