"""NVIDIA GPU manager (reference:
python/ray/_private/accelerators/nvidia_gpu.py). Present so mixed
clusters (CPU/GPU hosts driving TPU slices) schedule correctly; the
TPU path never uses it."""

from __future__ import annotations

import glob
from functools import lru_cache

from .base import AcceleratorManager


class NvidiaGPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "GPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return "CUDA_VISIBLE_DEVICES"

    @staticmethod
    @lru_cache()
    def get_current_node_num_accelerators() -> int:
        import os

        override = os.environ.get("RT_NUM_GPUS")
        if override is not None:
            return int(override)
        return len(glob.glob("/proc/driver/nvidia/gpus/*"))
