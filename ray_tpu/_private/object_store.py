"""Node-local object stores.

Two tiers, mirroring the reference's split between the in-process
memory store for small objects and the plasma shared-memory store for
large ones (reference: src/ray/core_worker/store_provider/,
src/ray/object_manager/plasma/store.h):

* Small objects (≤ max_direct_call_object_size) are inlined into task
  specs/replies and live in the node daemon's object table (the
  in-process memory-store tier of the reference).

* `SharedMemoryStore` — immutable shared-memory objects, one POSIX SHM
  segment per object, readable zero-copy by every process on the node.
  Plasma's mmap-arena + dlmalloc design (plasma/dlmalloc.cc) is an
  allocation optimization we trade away for per-object segments, which
  the kernel already refcounts; create/seal/get/delete and LRU eviction
  semantics are preserved (plasma/object_lifecycle_manager.h,
  eviction_policy.h).

Both stores hand out `memoryview`s so deserialization is zero-copy all
the way into numpy / `jax.numpy.asarray`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, Optional

from .ids import ObjectID


class ObjectStoreFullError(Exception):
    pass


class ObjectNotSealedError(Exception):
    pass


def _close_shm(shm: shared_memory.SharedMemory) -> None:
    """Close a segment, tolerating live zero-copy views.

    numpy/jax arrays deserialized from the store keep memoryview
    exports into the mapping; releasing then raises BufferError. The
    segment is already unlinked by callers, so we drop our handles and
    let the pages die with the last view (avoids "Exception ignored in
    __del__" noise at interpreter exit).
    """
    try:
        shm.close()
    except BufferError:
        shm._buf = None  # noqa: SLF001 — disarm SharedMemory.__del__
        shm._mmap = None  # noqa: SLF001
        fd = getattr(shm, "_fd", -1)
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass
            shm._fd = -1  # noqa: SLF001


def _unregister(shm: shared_memory.SharedMemory) -> None:
    # Python's resource_tracker unlinks SHM segments when *any* process
    # that attached exits, which would tear objects out from under
    # other readers. The store owns lifetime explicitly, so opt out.
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


@dataclass
class _Entry:
    shm: shared_memory.SharedMemory
    size: int
    sealed: bool
    created_at: float
    pinned: int = 0  # pin count: primary copies pinned by the node
                     # daemon are never evicted (reference:
                     # raylet/local_object_manager.h primary pinning)


class SharedMemoryStore:
    """Create/seal/get over per-object shared-memory segments.

    The process that calls `create` writes into the returned buffer and
    then calls `seal`; readers in any process call `get`/`open` and map
    the same pages. Objects are immutable after seal.
    """

    def __init__(
        self,
        node_id_hex: str,
        capacity: int,
        on_evict=None,
        evict_enabled: bool = True,
    ):
        self._prefix = f"rt_{node_id_hex[:8]}_"
        self._capacity = capacity
        self._used = 0
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._lock = threading.RLock()
        self._seal_events: Dict[ObjectID, threading.Event] = {}
        # Called with each evicted ObjectID so the owning daemon can fix
        # its object table / tell the control plane a copy is gone.
        self._on_evict = on_evict
        # Worker/driver instances must NOT evict: bookkeeping is
        # per-process, so a client-side LRU pass could destroy a
        # primary copy the daemon believes is pinned. Clients raise
        # ObjectStoreFullError instead; the daemon spills, and the
        # client reclaims accounting for the vanished segments via
        # _sweep_unlinked.
        self._evict_enabled = evict_enabled

    # -- producer side ---------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> memoryview:
        size = max(size, 1)
        with self._lock:
            if object_id in self._entries:
                raise ValueError(f"Object {object_id} already exists")
            if self._used + size > self._capacity:
                self._sweep_unlinked()
            if self._used + size > self._capacity and self._evict_enabled:
                self._evict(self._used + size - self._capacity)
            if self._used + size > self._capacity:
                raise ObjectStoreFullError(
                    f"need {size} bytes, store has "
                    f"{self._capacity - self._used} free of {self._capacity}"
                )
            shm = shared_memory.SharedMemory(
                name=self._name(object_id), create=True, size=size
            )
            _unregister(shm)
            self._entries[object_id] = _Entry(
                shm=shm, size=size, sealed=False, created_at=time.time()
            )
            self._used += size
            return shm.buf[:size]

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries[object_id]
            entry.sealed = True
            event = self._seal_events.pop(object_id, None)
        if event is not None:
            event.set()

    def put(self, object_id: ObjectID, data: bytes | memoryview) -> None:
        buf = self.create(object_id, len(data))
        buf[: len(data)] = data
        self.seal(object_id)

    # -- consumer side ---------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get(
        self, object_id: ObjectID, timeout: Optional[float] = None
    ) -> Optional[memoryview]:
        """Return a zero-copy view of a sealed object, waiting if needed."""
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.sealed:
                self._entries.move_to_end(object_id)  # LRU touch
                return entry.shm.buf[: entry.size]
            event = self._seal_events.setdefault(object_id, threading.Event())
        while True:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return None
            if event.wait(timeout=remaining if remaining else 0.05):
                break
            with self._lock:
                entry = self._entries.get(object_id)
                if entry is not None and entry.sealed:
                    break
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed:
                return None
            return entry.shm.buf[: entry.size]

    def open_remote(self, object_id: ObjectID, size: int) -> memoryview:
        """Attach to a segment created by another process on this node."""
        shm = shared_memory.SharedMemory(name=self._name(object_id))
        _unregister(shm)
        with self._lock:
            if object_id not in self._entries:
                self._entries[object_id] = _Entry(
                    shm=shm, size=size, sealed=True, created_at=time.time()
                )
                # Attached segments count against capacity the same as
                # created ones — delete()/evict subtract them later.
                self._used += size
        return shm.buf[:size]

    # -- lifetime --------------------------------------------------------
    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            if object_id in self._entries:
                self._entries[object_id].pinned += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            if object_id in self._entries:
                self._entries[object_id].pinned = max(
                    0, self._entries[object_id].pinned - 1
                )

    def unlink_by_id(self, object_id: ObjectID) -> None:
        """Unlink a segment this process never attached (the daemon
        owns lifetime but clients create segments directly)."""
        with self._lock:
            if object_id in self._entries:
                pass  # fall through to normal delete below
            else:
                try:
                    shm = shared_memory.SharedMemory(
                        name=self._name(object_id)
                    )
                    _unregister(shm)
                    shm.unlink()
                    shm.close()
                except FileNotFoundError:
                    pass
                return
        self.delete(object_id, unlink=True)

    def delete(self, object_id: ObjectID, unlink: bool = True) -> None:
        with self._lock:
            entry = self._entries.pop(object_id, None)
            if entry is not None:
                self._used -= entry.size
        if entry is not None:
            if unlink:
                try:
                    entry.shm.unlink()
                except FileNotFoundError:
                    pass
            _close_shm(entry.shm)

    def size_info(self) -> dict:
        with self._lock:
            return {
                "capacity": self._capacity,
                "used": self._used,
                "num_objects": len(self._entries),
            }

    def _evict(self, bytes_needed: int) -> None:
        """LRU eviction of unpinned sealed objects (caller holds lock)."""
        freed = 0
        victims = [
            oid
            for oid, e in self._entries.items()
            if e.sealed and e.pinned == 0
        ]
        evicted = []
        for oid in victims:
            if freed >= bytes_needed:
                break
            entry = self._entries.pop(oid)
            freed += entry.size
            self._used -= entry.size
            try:
                entry.shm.unlink()
            except FileNotFoundError:
                pass
            _close_shm(entry.shm)
            evicted.append(oid)
        if self._on_evict is not None:
            for oid in evicted:
                try:
                    self._on_evict(oid)
                except Exception:
                    pass

    def _sweep_unlinked(self) -> None:
        """Reclaim accounting for segments whose backing /dev/shm file
        is gone — the daemon spilled or deleted them; this process's
        per-instance bookkeeping just hasn't heard (caller holds lock).
        Pages stay alive for any live zero-copy views; only the
        capacity charge is dropped."""
        for oid in list(self._entries):
            entry = self._entries[oid]
            name = entry.shm._name.lstrip("/")  # noqa: SLF001
            if not os.path.exists("/dev/shm/" + name):
                del self._entries[oid]
                self._used -= entry.size
                _close_shm(entry.shm)

    def _name(self, object_id: ObjectID) -> str:
        return self._prefix + object_id.hex()

    def shutdown(self, unlink: bool = True) -> None:
        with self._lock:
            for oid in list(self._entries):
                self.delete(oid, unlink=unlink)


class ArenaPin:
    """A reader lease on one arena object (plasma buffer analog).

    Holds the slot pinned — unevictable and undeletable — until
    release(), which is idempotent and safe after arena close. The
    worker ties release to the lifetime of the zero-copy buffers it
    hands out (see _TrackedBuffer), matching plasma's Release-on-
    buffer-destruction protocol."""

    __slots__ = ("_arena", "view", "_index", "_released")

    def __init__(self, arena, view: memoryview, index: int):
        self._arena = arena
        self.view = view
        self._index = index
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._arena.unpin_idx(self._index)


def transfer_pin_to_exporter(pin: ArenaPin) -> None:
    """Hand a pin's release to the lifetime of its zero-copy views.

    Every NativeArena view is exported from a PER-PIN ctypes array
    (`NativeArena._view`): memoryviews sliced from it — including
    numpy arrays reconstructed over out-of-band buffers — keep that
    exporter object alive, so a weakref.finalize on the exporter
    fires exactly when the last zero-copy view is garbage-collected
    (plasma's Release-on-buffer-destruction, without the PEP 688
    wrapper this replaced — works on every supported interpreter,
    where the old pure-Python __buffer__ path forced a full copy-out
    below 3.12).

    The finalizer must not close over the pin or its view: finalize
    holds its callback arguments strongly, and pin -> view -> exporter
    would pin the exporter (and the slot) forever. Only the arena
    handle and slot index ride along; arena close() makes a late
    unpin a guarded no-op."""
    import weakref

    exporter = pin.view.obj  # the ctypes array backing every slice
    arena, index = pin._arena, pin._index  # noqa: SLF001 — same module family
    pin.view = None
    pin._released = True  # the exporter owns the release now
    weakref.finalize(exporter, arena.unpin_idx, index)


class NativeArenaStore:
    """Same surface as SharedMemoryStore over the C++ arena
    (_native/store.cc): one mmap'd /dev/shm file per node, first-fit
    free-list allocator, process-shared index, LRU eviction in native
    code. Accounting is arena-global (every process sees one shared
    used/capacity), unlike the per-process bookkeeping above.

    Enabled via config `use_native_object_store` (RT_use_native_object_
    store=1). Readers are protected plasma-style: acquire() pins the
    slot (pins block LRU eviction and defer deletion in store.cc) and
    the returned ArenaPin releases when its zero-copy views die.
    Crashed readers' pins are reclaimed by the daemon's periodic
    reap_dead_pins() (plasma reclaims on client disconnect).
    """

    needs_release = True  # consumers must use acquire()/ArenaPin

    def __init__(self, node_id_hex: str, capacity: int, on_evict=None):
        from .._native import NativeArena

        self._path = f"/dev/shm/rt_arena_{node_id_hex[:8]}"
        self._arena = NativeArena(self._path, capacity, create=True)
        self._on_evict = on_evict
        self._capacity = capacity
        self._seal_events: Dict[ObjectID, threading.Event] = {}
        self._lock = threading.Lock()
        self._shutdown_done = False

    def _notify_evicted(self, raw_ids) -> None:
        if self._on_evict is None:
            return
        for raw in raw_ids:
            try:
                self._on_evict(ObjectID(raw[: ObjectID.SIZE]))
            except Exception:
                pass

    # -- producer side -------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> memoryview:
        try:
            view, evicted = self._arena.create(object_id.binary(), size)
        except MemoryError as e:
            raise ObjectStoreFullError(str(e)) from None
        self._notify_evicted(evicted)
        return view

    def seal(self, object_id: ObjectID) -> None:
        self._arena.seal(object_id.binary())
        with self._lock:
            event = self._seal_events.pop(object_id, None)
        if event is not None:
            event.set()

    def seal_pinned(self, object_id: ObjectID) -> Optional[ArenaPin]:
        """Seal + creator pin in one arena critical section: a fresh
        SEALED slot with zero pins is an LRU victim, so the creator
        holds this pin until the daemon's primary pin is registered
        (closes the seal->report eviction window). Seal failures raise
        exactly like seal() — a silent None here would let callers
        report an object that doesn't exist."""
        pinned = self._arena.seal_pinned(object_id.binary())
        if pinned is None:
            # Surface the real error (missing slot / bad state) with
            # seal()'s raising semantics; the seal event stays unset.
            self._arena.seal(object_id.binary())
            # seal() somehow succeeded after seal_pinned failed (can
            # only happen if the two raced a delete+recreate): sealed,
            # but no pin to hand out.
        with self._lock:
            event = self._seal_events.pop(object_id, None)
        if event is not None:
            event.set()
        if pinned is None:
            return None
        index, view = pinned
        return ArenaPin(self._arena, view, index)

    def put(self, object_id: ObjectID, data) -> None:
        buf = self.create(object_id, len(data))
        buf[: len(data)] = data
        self.seal(object_id)

    # -- consumer side -------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        return self._arena.contains(object_id.binary())

    def _try_acquire(self, object_id: ObjectID) -> Optional[ArenaPin]:
        """Atomic pin+view (store.cc rts_pin) so the returned view is
        guaranteed to map the pinned slot — immune both to concurrent
        eviction and to delete/re-create ABA on the same oid."""
        pinned = self._arena.try_pin(object_id.binary())
        if pinned is None:
            return None
        index, view = pinned
        return ArenaPin(self._arena, view, index)

    def acquire(
        self, object_id: ObjectID, timeout: Optional[float] = None
    ) -> Optional[ArenaPin]:
        """Pinned zero-copy read lease; None if not sealed in time."""
        pin = self._try_acquire(object_id)
        if pin is not None:
            return pin
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            event = self._seal_events.setdefault(
                object_id, threading.Event()
            )
        try:
            while True:
                remaining = (
                    None if deadline is None else deadline - time.time()
                )
                if remaining is not None and remaining <= 0:
                    return None
                # Same-process seals signal the event; cross-process
                # seals are observed by polling the shared index.
                event.wait(timeout=min(remaining or 0.005, 0.005))
                pin = self._try_acquire(object_id)
                if pin is not None:
                    return pin
        finally:
            # Cross-process seals never pop the event in seal(); drop
            # it here so long-lived consumers don't accumulate one
            # Event per object ever fetched.
            with self._lock:
                self._seal_events.pop(object_id, None)

    def reap_dead_pins(self) -> int:
        return self._arena.reap_dead_pins()

    def unlink_by_id(self, object_id: ObjectID) -> None:
        self._arena.delete(object_id.binary())

    def delete(self, object_id: ObjectID, unlink: bool = True) -> None:
        self._arena.delete(object_id.binary())

    def size_info(self) -> dict:
        return self._arena.stats()

    def shutdown(self, unlink: bool = True) -> None:
        if self._shutdown_done:
            return
        self._shutdown_done = True
        try:
            self._arena.close(unlink=unlink)
        except Exception:
            pass


def make_store(
    node_id_hex: str,
    capacity: int,
    on_evict=None,
    use_native: bool = False,
    client: bool = False,
):
    """Store factory: native arena when requested and buildable, else
    the per-segment Python store. `client=True` marks worker/driver
    instances, whose py-store bookkeeping is per-process and must never
    LRU-evict (the daemon owns eviction and spilling)."""
    if use_native:
        try:
            return NativeArenaStore(
                node_id_hex, capacity, on_evict=on_evict
            )
        except Exception:
            pass
    return SharedMemoryStore(
        node_id_hex, capacity, on_evict=on_evict,
        evict_enabled=not client,
    )
