"""Object spilling: overflow sealed objects from the shared-memory
store to session-local files and restore them on demand.

Reference behavior matched: the raylet's LocalObjectManager spills
under store pressure and restores on get (reference:
src/ray/raylet/local_object_manager.h:41,110 SpillObjectsOfSize /
AsyncRestoreSpilledObject) over a filesystem external storage
(reference: python/ray/_private/external_storage.py:72
FileSystemStorage — one directory of spill files keyed by object id).

TPU-first simplifications: one file per object (no multi-object
fusing — the kernel page cache already amortizes small reads, and the
store inlines sub-100KB objects anyway so spilled objects are large),
synchronous writes on the daemon's spill thread, and restore-by-read
into the same store the object left.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .ids import ObjectID


class FileSpillStorage:
    """Filesystem-backed external storage for spilled objects."""

    def __init__(self, spill_dir: str):
        self._dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._sizes: dict[ObjectID, int] = {}
        self._total = 0

    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self._dir, oid.hex())

    def spill(self, oid: ObjectID, view) -> int:
        """Write one sealed object's bytes to its spill file.

        Idempotent: re-spilling an already-spilled object is a no-op
        (the immutable-object invariant means the bytes cannot have
        changed), which makes repeated pressure cycles cheap.
        """
        with self._lock:
            if oid in self._sizes:
                return self._sizes[oid]
        path = self._path(oid)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(view)
        os.replace(tmp, path)  # atomic: readers never see partial files
        size = len(view)
        with self._lock:
            if oid not in self._sizes:
                self._sizes[oid] = size
                self._total += size
        return size

    def contains(self, oid: ObjectID) -> bool:
        # The disk probe runs under the lock so a concurrent delete()
        # (pop + unlink, also under the lock) can't interleave between
        # the exists check and the size read and resurrect a stale
        # entry.
        with self._lock:
            if oid in self._sizes:
                return True
            # A restarted daemon over the same session dir can still
            # serve files spilled by its predecessor.
            try:
                size = os.path.getsize(self._path(oid))
            except OSError:
                return False
            self._sizes[oid] = size
            self._total += size
            return True

    def size(self, oid: ObjectID) -> Optional[int]:
        with self._lock:
            return self._sizes.get(oid)

    def read(
        self, oid: ObjectID, offset: int = 0, length: Optional[int] = None
    ) -> Optional[bytes]:
        try:
            with open(self._path(oid), "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read() if length is None else f.read(length)
        except FileNotFoundError:
            return None

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            size = self._sizes.pop(oid, None)
            if size is not None:
                self._total -= size
            try:
                os.unlink(self._path(oid))
            except FileNotFoundError:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "spilled_objects": len(self._sizes),
                "spilled_bytes": self._total,
            }

    def shutdown(self) -> None:
        with self._lock:
            oids = list(self._sizes)
        for oid in oids:
            self.delete(oid)
        try:
            os.rmdir(self._dir)
        except OSError:
            pass
