"""Per-step, per-worker train-loop telemetry (runtime core).

PR 4 proved the input pipeline and checkpointing can be driven off the
step's critical path — but only bench.py could SHOW it. This module
moves that attribution into the runtime, always on: the data plane
(data/dataset.py), the H2D prefetcher (train/train_step.py), and the
checkpoint writer (train/checkpoint.py) accumulate per-phase wall time
into a thread-local, and the session's per-step report() folds them
into ONE record per (step index, worker rank):

    {step, rank, wall_ms, data_wait_ms, h2d_ms, ckpt_block_ms,
     step_ms, ckpt_inflight}

Records ride the existing metrics pipe (util/metrics._Buffer — one
batched RPC every 0.5 s, nothing per step) as kind="step" and land in
the head's step ring, where `step_summary` computes gang-step skew
(max - min step_ms across workers of the same step index) — the
number that answers "why is step N slow, and which worker is the
straggler" (PAPERS: Podracer architectures; per-stage timing
attribution per arXiv 2412.14374).

Lives in _private so the data layer can import it without dragging in
the jax-importing train package; `ray_tpu.train.telemetry` re-exports
the user-facing surface.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

__all__ = [
    "add_phase",
    "take_phases",
    "phase_timer",
    "timed_iter",
    "report_step",
    "steps_to_chrome_trace",
    "goodput_from_records",
    "stalls_active",
]

_tl = threading.local()


def _phases() -> Dict[str, float]:
    phases = getattr(_tl, "phases", None)
    if phases is None:
        phases = _tl.phases = {}
    return phases


def add_phase(name: str, ms: float) -> None:
    """Accumulate `ms` of wall time into the current thread's phase
    bucket (drained by the next report_step on this thread)."""
    phases = _phases()
    phases[name] = phases.get(name, 0.0) + float(ms)


def take_phases() -> Dict[str, float]:
    """Pop-and-reset the current thread's accumulated phases.

    Also the baseline drain for hand-rolled loops: call it once right
    before the step loop starts so stall time accumulated during setup
    (preprocessing passes over instrumented iterators) is not billed
    to the first step's report_step(). Sessions do this automatically
    at construction."""
    phases = getattr(_tl, "phases", None)
    _tl.phases = {}
    return phases or {}


class phase_timer:
    """Context manager billing a consumer-visible stall into `phase`.

    Reentrancy-safe per (thread, phase): only the OUTERMOST active
    timer records. An inner timed region — e.g. a telemetry-wrapped
    iterator pulled through a user's generator transform into
    prefetch_to_device — is already inside the outer timer's wall,
    and billing both would double-count the same stall (driving the
    derived step_ms = wall - waits negative)."""

    __slots__ = ("_phase", "_outer", "_t0")

    def __init__(self, phase: str):
        self._phase = phase

    def __enter__(self) -> "phase_timer":
        depths = getattr(_tl, "depths", None)
        if depths is None:
            depths = _tl.depths = {}
        self._outer = not depths.get(self._phase)
        depths[self._phase] = depths.get(self._phase, 0) + 1
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tl.depths[self._phase] -= 1
        # Exhaustion (StopIteration) and errors don't bill the phase.
        if self._outer and exc_type is None:
            add_phase(
                self._phase, (time.monotonic() - self._t0) * 1e3
            )
        return False


def stalls_active() -> bool:
    """True when any phase_timer is currently open on this thread.

    The worker's get path uses this to bill ``get_wait_ms`` only when
    no enclosing instrumented phase (data_wait, h2d, send/recv, ...)
    is already measuring the same wall — otherwise a get() issued
    inside a timed data iterator would be billed twice and the phases
    would stop partitioning the step wall."""
    depths = getattr(_tl, "depths", None)
    return bool(depths) and any(depths.values())


class _TimedIterator:
    """Iterator wrapper accumulating the consumer-visible blocked time
    of each next() into a named phase. The wrap happens at the
    OUTERMOST boundary (post-prefetch), so what's measured is the
    stall the train loop actually pays, not producer-side work that
    overlapped compute. Stacked instrumentation (prefetch_to_device,
    or a user transform over one of these) never double-counts
    because every layer times through the reentrancy-guarded
    phase_timer."""

    def __init__(self, iterator: Iterator[Any], phase: str):
        self._it = iter(iterator)
        self._phase = phase

    def __iter__(self) -> "_TimedIterator":
        return self

    def __next__(self) -> Any:
        with phase_timer(self._phase):
            return next(self._it)

    def close(self) -> None:
        # Cascading cancellation (dataset._prefetched relies on it).
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


def timed_iter(
    iterator: Iterator[Any], phase: str = "data_wait_ms"
) -> _TimedIterator:
    return _TimedIterator(iterator, phase)


#: Phase layout order inside a step slice: the waits the loop paid
#: before/around the step, then the step itself. send/recv wait are
#: the MPMD pipeline's channel-blocked time (dag/edges.py bills them)
#: — the per-stage bubble attribution the pipeline doctor reads.
#: queue_wait is the decoupled RL dataflow's rollout-queue stall
#: (rl/dataflow.py bills it) — the learner starving on rollouts,
#: billed exactly like a trainer starving on input (data_wait);
#: weight_sync is its drainless weight-publish stall. compile is XLA
#: trace+compile time (_private/compile_watch.py bills it on digest
#: misses) — the cold-compile step's cost, attributed instead of
#: masquerading as a giant step_ms. get_wait is object-plane blocked
#: time: rt.get() waits billed by worker._record_get with the
#: resolution's provenance (pull vs restore vs local — the transfer
#: matrix says which), only when no enclosing phase already measures
#: the same wall (see stalls_active).
_TRACE_PHASES = (
    "data_wait_ms",
    "get_wait_ms",
    "queue_wait_ms",
    "h2d_ms",
    "ckpt_block_ms",
    "weight_sync_ms",
    "send_wait_ms",
    "recv_wait_ms",
    "compile_ms",
    "step_ms",
)


def steps_to_chrome_trace(records) -> list:
    """Per-step, per-rank phase records (the head's step ring) ->
    chrome trace 'X' slices: one row per worker rank, one slice per
    phase, consecutive steps of a rank laid end-to-end. Timestamps
    are synthesized (records carry durations plus the head's arrival
    time — which is the BATCH arrival, shared by every step delivered
    in one metrics flush, so arrival times alone would stack a
    flush's steps on top of each other) — widths and per-rank
    alignment are the signal, matching what gang-skew diagnosis
    needs."""
    by_rank: dict = {}
    for rec in records:
        by_rank.setdefault(int(rec.get("rank", 0)), []).append(rec)
    trace = []
    for rank, recs in sorted(by_rank.items()):
        recs.sort(
            key=lambda r: (
                int(r.get("step", 0)),
                float(r.get("time", 0.0)),
            )
        )
        cursor_us = None
        for rec in recs:
            step = int(rec.get("step", 0))
            # Warmup (first-report) records anchor their wall at
            # session construction and derive step_ms from it — both
            # setup-dominated; laying either out would draw a giant
            # phantom step-1 slice. Draw only the measured waits.
            if rec.get("warmup"):
                trace_phases = _TRACE_PHASES[:-1]
                wall_ms = 0.0
            else:
                trace_phases = _TRACE_PHASES
                wall_ms = float(rec.get("wall_ms", 0.0) or 0.0)
            if wall_ms <= 0.0:
                wall_ms = sum(
                    float(rec.get(p, 0.0) or 0.0)
                    for p in trace_phases
                )
            if cursor_us is None:
                end_t = float(rec.get("time", 0.0))
                cursor_us = (end_t - wall_ms / 1e3) * 1e6
            step_start_us = cursor_us
            for phase in trace_phases:
                dur_ms = float(rec.get(phase, 0.0) or 0.0)
                if dur_ms <= 0.0:
                    continue
                trace.append(
                    {
                        "name": f"step {step} {phase[:-3]}",
                        "cat": "step",
                        "ph": "X",
                        "ts": cursor_us,
                        "dur": max(1.0, dur_ms * 1e3),
                        "pid": "steps",
                        "tid": f"rank {rank}",
                        "args": {"step": step, "rank": rank},
                    }
                )
                cursor_us += dur_ms * 1e3
            # Steps whose phases undershoot the wall interval still
            # advance a full wall window — the gap IS unattributed
            # time, not overlap.
            cursor_us = max(
                cursor_us, step_start_us + wall_ms * 1e3
            )
    return trace


#: Wait phases that classify as stall time in goodput accounting.
#: send/recv wait are pipeline-channel blocked time: for an MPMD
#: stage, that IS the (bubble + transport) share of its wall.
#: queue_wait/weight_sync are the RL dataflow's consume-side stalls —
#: a learner whose goodput is eaten by queue_wait is runner-bound,
#: one eaten by weight_sync is sync-bound (doctor's verdict.rl reads
#: the same attribution from the rl_* series).
#: compile is XLA's share of the wall: a loop whose goodput is eaten
#: by compile_ms is recompiling (see verdict.compile), not slow.
#: get_wait is the object plane's share: goodput eaten here means the
#: loop blocks on rt.get — /api/transfers says whether those bytes
#: were pulls, restores, or misplacement (README runbook).
_STALL_PHASES = (
    "data_wait_ms",
    "get_wait_ms",
    "queue_wait_ms",
    "h2d_ms",
    "ckpt_block_ms",
    "weight_sync_ms",
    "send_wait_ms",
    "recv_wait_ms",
    "compile_ms",
)


def goodput_from_records(records) -> Dict[str, dict]:
    """Classify each job's reported step wall clock into productive
    vs stall time (PAPERS: the Gemma-on-TPU serving/fine-tuning
    comparison hinges on sustained-throughput accounting — goodput is
    its training-side analog).

    Per job: ``wall_ms`` = sum of non-warmup step walls, split into
    ``productive_ms`` (step compute), per-phase ``stalls``
    (`data_wait`/`h2d`/`ckpt_block`) and ``idle_ms`` (wall the phases
    don't attribute). By construction productive + stall + idle == wall
    exactly: phases are capped at the wall they sit inside (the same
    cap `report_step` applies), so the goodput fraction is a true
    fraction of measured wall clock, never >1 and never negative.

    Warmup records (session setup) and records with no wall anchor
    (hand-rolled `report_step(step_ms=...)` without `wall_ms`) carry
    no usable wall interval and are skipped; `steps` counts what was
    actually classified.
    """
    jobs: Dict[str, dict] = {}
    for rec in records:
        if rec.get("warmup"):
            continue
        try:
            wall = float(rec.get("wall_ms", 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        if wall <= 0.0:
            continue
        job = str(rec.get("job", ""))
        row = jobs.setdefault(
            job,
            {
                "steps": 0,
                "wall_ms": 0.0,
                "productive_ms": 0.0,
                "stall_ms": 0.0,
                "idle_ms": 0.0,
                "stalls": {p: 0.0 for p in _STALL_PHASES},
            },
        )
        stall = 0.0
        for phase in _STALL_PHASES:
            try:
                ms = float(rec.get(phase, 0.0) or 0.0)
            except (TypeError, ValueError):
                ms = 0.0
            # A stall inside this step's wall cannot exceed the wall
            # REMAINING after the stalls already counted.
            ms = max(0.0, min(ms, wall - stall))
            row["stalls"][phase] += ms
            stall += ms
        try:
            productive = float(rec.get("step_ms", 0.0) or 0.0)
        except (TypeError, ValueError):
            productive = 0.0
        productive = max(0.0, min(productive, wall - stall))
        row["steps"] += 1
        row["wall_ms"] += wall
        row["productive_ms"] += productive
        row["stall_ms"] += stall
        row["idle_ms"] += wall - stall - productive
    for row in jobs.values():
        wall = row["wall_ms"]
        row["goodput"] = round(
            row["productive_ms"] / wall if wall > 0 else 0.0, 4
        )
        for key in ("wall_ms", "productive_ms", "stall_ms", "idle_ms"):
            row[key] = round(row[key], 3)
        row["stalls"] = {
            p: round(v, 3) for p, v in row["stalls"].items()
        }
    return jobs


def report_step(
    step: int,
    *,
    rank: int = 0,
    step_ms: Optional[float] = None,
    wall_ms: Optional[float] = None,
    extra: Optional[dict] = None,
) -> None:
    """Emit one per-step phase record through the metrics pipe.

    Called by the session on every train.report(); usable directly
    from hand-rolled loops — which should call take_phases() once
    before their loop starts, so stall time accumulated during setup
    is not billed to the first step. `step_ms` defaults to the wall
    interval minus the accumulated wait phases — the residual that IS
    the step's compute + dispatch. Outside a session (no initialized
    worker) the accumulated phases are dropped silently: telemetry
    must never make a unit test need a cluster.
    """
    from .worker import global_worker

    worker = global_worker()
    if worker is None:
        take_phases()
        return
    phases = take_phases()
    if wall_ms is not None:
        # A consumer-visible stall inside this step's wall interval
        # cannot exceed the interval — excess is accumulation from
        # BEFORE the loop (a hand-rolled loop that skipped the
        # take_phases() baseline drain); billing it would misdirect
        # the input-pipeline-vs-step runbook decision.
        cap = max(0.0, float(wall_ms))
        for name in phases:
            if phases[name] > cap:
                phases[name] = cap
    # pid + node identify the REPORTING PROCESS: the doctor reads
    # them as its liveness signal (a worker with a recent step record
    # is progressing — its long-lived fit task is not hung). `job`
    # keeps step stats from different training jobs apart — the
    # head's summary is computed per job, never over a mixture.
    record: Dict[str, Any] = {
        "rank": int(rank),
        "pid": os.getpid(),
        "node": worker.node_id.hex(),
        "job": worker.job_id.hex(),
    }
    # The executing task's id (thread-local): lets the doctor exempt
    # exactly the reporting train-loop task, not everything that
    # happens to share its process (a concurrent actor's OTHER call
    # may be genuinely hung).
    task_id = getattr(worker._ctx, "task_id", None)
    if task_id is not None:
        record["task"] = task_id.hex()
    for name, ms in phases.items():
        record[name] = round(ms, 3)
    if wall_ms is not None:
        record["wall_ms"] = round(float(wall_ms), 3)
    if step_ms is None and wall_ms is not None:
        step_ms = max(
            0.0,
            float(wall_ms)
            - sum(phases.get(p, 0.0) for p in _STALL_PHASES),
        )
    try:
        record["step_ms"] = round(float(step_ms or 0.0), 3)
    except (TypeError, ValueError):
        record["step_ms"] = 0.0
    try:
        from ..train.checkpoint import pending_checkpoints

        record["ckpt_inflight"] = len(pending_checkpoints())
    except Exception:
        pass
    if extra:
        record.update(extra)
    from ..util.metrics import _Buffer

    buf = _Buffer.get()
    # Per-rank HBM occupancy from device.memory_stats(), folded into
    # the same step record (and exported as (job, rank)-labeled
    # gauges — both bounded, and without the job label two jobs'
    # same-numbered ranks would clobber one series). None on CPU or
    # when the runtime exposes no stats: the fields are ABSENT, never
    # fake zeros that would read as "no pressure".
    from .compile_watch import device_memory

    hbm = device_memory()
    if hbm:
        hbm_tags = (
            ("job", record["job"]),
            ("rank", str(int(rank))),
        )
        for key, value in hbm.items():
            record[key] = int(value)
            buf.push(
                ("gauge", "rt_" + key, float(value), hbm_tags)
            )
    buf.push(
        (
            "step",
            "train_step",
            float(step),
            tuple(sorted(record.items())),
        )
    )
