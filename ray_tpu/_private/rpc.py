"""Minimal message-passing RPC over Unix domain sockets.

Plays the role of the reference's gRPC wrappers (reference:
src/ray/rpc/grpc_server.h, grpc_client.h, retryable_grpc_client.h):
length-prefixed pickled dict messages, a threaded server dispatching to
registered handlers, and a client with request/response correlation,
server-push subscriptions, retry with exponential backoff, and the
same fault-injection hook the reference exposes for chaos testing
(rpc_chaos.h:23-31 — `RT_testing_rpc_failure="method=count"` drops the
first `count` calls of `method`).

Wire format: 8-byte big-endian length + pickled dict. Every message
carries `_mid` (correlation id); server replies echo it; unsolicited
pushes use `_mid = -1` and a `_push` channel name.
"""

from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

_LEN = struct.Struct(">Q")


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


# ---------------------------------------------------------------------------
# chaos / fault injection
# ---------------------------------------------------------------------------

_chaos_lock = threading.Lock()
_chaos_budget: Dict[str, int] = {}


def configure_chaos(spec: str) -> None:
    """Parse "method=count,method2=count2" fault-injection spec."""
    with _chaos_lock:
        _chaos_budget.clear()
        for part in filter(None, spec.split(",")):
            method, _, count = part.partition("=")
            _chaos_budget[method.strip()] = int(count or 1)


def _chaos_should_fail(method: str) -> bool:
    with _chaos_lock:
        left = _chaos_budget.get(method, 0)
        if left > 0:
            _chaos_budget[method] = left - 1
            return True
    return False


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def send_msg(sock: socket.socket, msg: dict) -> None:
    payload = pickle.dumps(msg, protocol=5)
    try:
        sock.sendall(_LEN.pack(len(payload)) + payload)
    except (BrokenPipeError, ConnectionResetError, OSError) as e:
        raise ConnectionLost(str(e)) from e


def recv_msg(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n > 0:
        try:
            chunk = sock.recv(min(n, 1 << 20))
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class RpcServer:
    """Threaded Unix-socket server dispatching named methods.

    Handlers run on per-connection reader threads; a handler may reply
    synchronously (return a dict) or later via the provided
    `Connection.push` / deferred reply handle.
    """

    def __init__(self, path: str):
        self._path = path
        self._handlers: Dict[str, Callable] = {}
        self._connections: Dict[int, "Connection"] = {}
        self._conn_counter = 0
        self._lock = threading.Lock()
        self._closed = False
        if os.path.exists(path):
            os.unlink(path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(128)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"rpc-accept:{path}", daemon=True
        )

    def register(self, method: str, handler: Callable) -> None:
        self._handlers[method] = handler

    def start(self) -> None:
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conn_counter += 1
                conn = Connection(self, sock, self._conn_counter)
                self._connections[conn.conn_id] = conn
            threading.Thread(
                target=conn.serve, name=f"rpc-conn-{conn.conn_id}", daemon=True
            ).start()

    def _dispatch(self, conn: "Connection", msg: dict) -> None:
        method = msg.get("_method", "")
        mid = msg.get("_mid")
        handler = self._handlers.get(method)
        if handler is None:
            if mid:
                conn.reply(mid, {"_error": f"no such method: {method}"})
            return
        try:
            result = handler(conn, msg)
        except Exception as e:  # noqa: BLE001 — errors propagate to caller
            import traceback

            if mid:
                conn.reply(
                    mid, {"_error": f"{e}\n{traceback.format_exc()}"}
                )
            return
        if result is not DEFERRED and mid:
            conn.reply(mid, result or {})

    def _on_disconnect(self, conn: "Connection") -> None:
        with self._lock:
            self._connections.pop(conn.conn_id, None)
        handler = self._handlers.get("_disconnect")
        if handler is not None:
            try:
                handler(conn, {})
            except Exception:
                pass

    def connections(self) -> list:
        with self._lock:
            return list(self._connections.values())

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in self.connections():
            conn.close()
        if os.path.exists(self._path):
            try:
                os.unlink(self._path)
            except OSError:
                pass


#: Sentinel a handler returns to indicate it will reply later via
#: `Connection.reply(mid, ...)` (used for blocking ops like object gets).
DEFERRED = object()


class Connection:
    """Server-side view of one client connection."""

    def __init__(self, server: RpcServer, sock: socket.socket, conn_id: int):
        self._server = server
        self._sock = sock
        self.conn_id = conn_id
        self._send_lock = threading.Lock()
        self.metadata: Dict[str, Any] = {}  # e.g. worker id after register

    def serve(self) -> None:
        while True:
            msg = recv_msg(self._sock)
            if msg is None:
                break
            self._server._dispatch(self, msg)
        self._server._on_disconnect(self)

    def reply(self, mid, payload: dict) -> None:
        payload = dict(payload)
        payload["_mid"] = mid
        with self._send_lock:
            try:
                send_msg(self._sock, payload)
            except ConnectionLost:
                pass

    def push(self, channel: str, payload: dict) -> None:
        payload = dict(payload)
        payload["_mid"] = -1
        payload["_push"] = channel
        with self._send_lock:
            try:
                send_msg(self._sock, payload)
            except ConnectionLost:
                pass

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RpcClient:
    """Thread-safe client with correlation ids, pushes, and retries."""

    def __init__(
        self,
        path: str,
        push_handler: Optional[Callable[[str, dict], None]] = None,
        connect_timeout: float = 10.0,
    ):
        self._path = path
        self._push_handler = push_handler
        self._sock = self._connect(connect_timeout)
        self._mid = 0
        self._lock = threading.Lock()
        # Serializes whole frames: call()/notify() run on arbitrary
        # threads (ObjectRef.__del__ fires on GC threads) and an
        # interleaved sendall would corrupt the length-prefixed wire.
        self._send_lock = threading.Lock()
        self._pending: Dict[int, threading.Event] = {}
        self._replies: Dict[int, dict] = {}
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"rpc-client:{path}", daemon=True
        )
        self._reader.start()

    def _connect(self, timeout: float) -> socket.socket:
        deadline = time.time() + timeout
        last_err: Exception | None = None
        while time.time() < deadline:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                sock.connect(self._path)
                return sock
            except (FileNotFoundError, ConnectionRefusedError) as e:
                last_err = e
                sock.close()
                time.sleep(0.05)
        raise ConnectionLost(f"cannot connect to {self._path}: {last_err}")

    def _read_loop(self) -> None:
        while not self._closed:
            msg = recv_msg(self._sock)
            if msg is None:
                break
            mid = msg.get("_mid")
            if mid == -1:
                if self._push_handler is not None:
                    try:
                        self._push_handler(msg.get("_push", ""), msg)
                    except Exception:
                        pass
                continue
            with self._lock:
                event = self._pending.pop(mid, None)
                if event is not None:
                    self._replies[mid] = msg
            if event is not None:
                event.set()
        # Connection lost: wake all waiters with an error.
        with self._lock:
            for mid, event in self._pending.items():
                self._replies[mid] = {"_error": "__connection_lost__"}
                event.set()
            self._pending.clear()

    def call(
        self,
        method: str,
        timeout: Optional[float] = None,
        retries: int = 0,
        **kwargs,
    ) -> dict:
        """Synchronous call; raises RpcError on handler error."""
        attempt = 0
        backoff = 0.1
        while True:
            if _chaos_should_fail(method):
                reply = {"_error": "__chaos_injected_failure__"}
            else:
                reply = self._call_once(method, timeout, kwargs)
            err = reply.get("_error")
            if err is None:
                return reply
            if attempt < retries and err in (
                "__chaos_injected_failure__",
                "__connection_lost__",
                "__timeout__",
            ):
                attempt += 1
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                if err == "__connection_lost__":
                    self._reconnect()
                continue
            raise RpcError(f"{method}: {err}")

    def _call_once(self, method, timeout, kwargs) -> dict:
        with self._lock:
            if self._closed:
                return {"_error": "__connection_lost__"}
            self._mid += 1
            mid = self._mid
            event = threading.Event()
            self._pending[mid] = event
        msg = dict(kwargs)
        msg["_method"] = method
        msg["_mid"] = mid
        try:
            with self._send_lock:
                send_msg(self._sock, msg)
        except ConnectionLost:
            with self._lock:
                self._pending.pop(mid, None)
            return {"_error": "__connection_lost__"}
        if not event.wait(timeout=timeout):
            with self._lock:
                self._pending.pop(mid, None)
            return {"_error": "__timeout__"}
        with self._lock:
            return self._replies.pop(mid)

    def notify(self, method: str, **kwargs) -> None:
        """Fire-and-forget message (no reply expected)."""
        msg = dict(kwargs)
        msg["_method"] = method
        msg["_mid"] = 0
        try:
            with self._send_lock:
                send_msg(self._sock, msg)
        except ConnectionLost:
            pass

    def _reconnect(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = self._connect(10.0)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True
        )
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
