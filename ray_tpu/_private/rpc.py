"""Minimal message-passing RPC over Unix-domain AND TCP sockets.

Plays the role of the reference's gRPC wrappers (reference:
src/ray/rpc/grpc_server.h, grpc_client.h, retryable_grpc_client.h):
length-prefixed authenticated pickled dict messages, a threaded server
dispatching to registered handlers, and a client with request/response
correlation, server-push subscriptions, retry with exponential backoff,
and the same fault-injection hook the reference exposes for chaos
testing (rpc_chaos.h:23-31 — `RT_testing_rpc_failure="method=count"`
drops the first `count` calls of `method`).

Addresses
---------
- Unix socket: a filesystem path (``/tmp/.../hostd.sock``) or
  ``unix:///tmp/.../hostd.sock`` — intra-host control plane.
- TCP: ``tcp://host:port`` — the cross-host (DCN) transport the
  reference runs on gRPC. A server may listen on both at once
  (``add_listener``): workers ride the Unix socket, remote daemons
  the TCP one, sharing one handler table and connection namespace.

Wire format & authentication
----------------------------
On accept, the server sends a hello
(``[8-byte length][16-byte nonce][1-byte protocol version]``); the
client verifies the version (mismatch -> clean RpcError) and both
sides derive the connection key
``HMAC(cluster_key, b"rt-conn" || nonce)``. Every subsequent frame is

    [8-byte length][32-byte HMAC-SHA256][payload]
    payload = [4-byte envelope len][protobuf Frame envelope][body]

(see wire.py / protocol.proto): the envelope carries version, method,
correlation id, and push channel in a typed protobuf schema; the body
is the pickled argument/reply dict, placed out of band so large object
chunks decode zero-copy. The HMAC is keyed by the connection key and
verified BEFORE any decoding — unauthenticated peers cannot reach the
deserializer (which is what makes a pickle body tolerable on TCP),
and a frame captured on one connection cannot be replayed on another
(different nonce). A frame that fails verification terminates the
connection. Server-side, every request is validated against its
per-method schema (wire.SCHEMAS) before dispatch. The cluster key
comes from ``auth_key`` / ``RT_AUTH_TOKEN``; daemons refuse to bind
TCP with the well-known local default (they auto-generate, see
NodeDaemon). Every message carries `_mid` (correlation id); server
replies echo it; unsolicited pushes use `_mid = -1` and a `_push`
channel name.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from .event_stats import stats as _event_stats
from .flight_recorder import recorder as _flight
from ray_tpu.devtools.lock_witness import note_blocking as _note_blocking
from .wire import (
    PROTOCOL_VERSION,
    ProtocolVersionError,
    decode_frame,
    encode_frame,  # noqa: F401 — contiguous-frame path for tests
    encode_frame_buffers,
)
from .wire import has_schema as _schema_known
from .wire import validate as _schema_validate

_LEN = struct.Struct(">Q")
_DIGEST_BYTES = 32
#: Hard per-frame cap, enforced BEFORE any payload is buffered: an
#: unauthenticated TCP peer can make the server allocate at most this
#: much per connection. Must exceed the largest legitimate frame
#: (object-transfer chunk, default 5 MiB, + KV function blobs).
_MAX_FRAME = int(os.environ.get("RT_RPC_MAX_FRAME", 1 << 28))  # 256 MiB


def default_auth_key() -> bytes:
    """Cluster auth token: RT_AUTH_TOKEN env, else a well-known local
    key — acceptable ONLY for single-host Unix-socket sessions
    (protected by session-dir file permissions). NodeDaemon refuses to
    run a TCP listener on this default: it generates a random token
    and exports it before binding (see daemon._ensure_tcp_auth)."""
    token = os.environ.get("RT_AUTH_TOKEN", "")
    return token.encode() if token else INSECURE_LOCAL_KEY


INSECURE_LOCAL_KEY = b"rt-insecure-local-session"


def _connection_key(cluster_key: bytes, nonce: bytes) -> bytes:
    return _hmac.new(
        cluster_key, b"rt-conn" + nonce, hashlib.sha256
    ).digest()


def parse_address(address: str) -> Union[Tuple[str, str], Tuple[str, str, int]]:
    """('unix', path) or ('tcp', host, port)."""
    if address.startswith("unix://"):
        return ("unix", address[len("unix://"):])
    if address.startswith("tcp://"):
        rest = address[len("tcp://"):]
        host, _, port = rest.rpartition(":")
        return ("tcp", host, int(port))
    if address.startswith("/") or os.sep in address:
        return ("unix", address)
    if ":" in address:
        host, _, port = address.rpartition(":")
        return ("tcp", host, int(port))
    raise ValueError(f"unparseable RPC address: {address!r}")


def _detect_host_ip() -> str:
    """Best-effort primary interface IP (the reference resolves node
    IPs the same way, services.py get_node_ip_address): route a UDP
    socket at a public address — no packets are sent — and read the
    chosen source address. Falls back to the hostname's address; a
    loopback result is advertised only with a loud warning since
    remote peers cannot dial it."""
    ip = None
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(("8.8.8.8", 80))
            ip = probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        pass
    if ip is None or ip.startswith("127."):
        try:
            ip = socket.gethostbyname(socket.gethostname())
        except OSError:
            ip = None
    if ip is None or ip.startswith("127."):
        import sys

        print(
            "[ray_tpu] WARNING: could not determine a dialable host "
            "IP for a wildcard TCP bind; advertising 127.0.0.1 — "
            "remote nodes will NOT reach this daemon. Pass an "
            "explicit --listen-host / listen_host.",
            file=sys.stderr,
        )
        ip = "127.0.0.1"
    return ip


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


# ---------------------------------------------------------------------------
# chaos / fault injection
# ---------------------------------------------------------------------------

_chaos_lock = threading.Lock()  # rt: noqa[RT004] — guards test-only chaos budgets, held for a dict op
_chaos_budget: Dict[str, int] = {}


def configure_chaos(spec: str) -> None:
    """Parse "method=count,method2=count2" fault-injection spec."""
    with _chaos_lock:
        _chaos_budget.clear()
        for part in filter(None, spec.split(",")):
            method, _, count = part.partition("=")
            _chaos_budget[method.strip()] = int(count or 1)


def _chaos_should_fail(method: str) -> bool:
    with _chaos_lock:
        left = _chaos_budget.get(method, 0)
        if left > 0:
            _chaos_budget[method] = left - 1
            return True
    return False


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_ZERO_DIGEST = b"\x00" * _DIGEST_BYTES


def _frame_mac(sock: socket.socket) -> bool:
    """Per-frame MAC policy: required on TCP (network peers), elided
    on AF_UNIX — same-host sockets are gated by session-dir file
    permissions and the connection handshake still proves key
    possession, so hashing every multi-megabyte object chunk twice
    per hop bought no security the kernel wasn't already providing
    (the reference's local gRPC plane runs plaintext for the same
    reason). The digest field stays in the layout (zero-filled) so
    framing is family-independent."""
    try:
        return sock.family != socket.AF_UNIX
    except Exception:
        return True


def send_msg(sock: socket.socket, msg: dict, key: bytes) -> None:
    buffers = encode_frame_buffers(msg)
    total = sum(len(b) for b in buffers)
    if _frame_mac(sock):
        mac = _hmac.new(key, None, hashlib.sha256)
        for buf in buffers:
            mac.update(buf)
        digest = mac.digest()
    else:
        digest = _ZERO_DIGEST
    try:
        # Scatter-gather: object-chunk payloads go from their source
        # buffer to the kernel with no user-space concatenation.
        _sendall_vectored(
            sock, [_LEN.pack(total) + digest, *buffers]
        )
    except (BrokenPipeError, ConnectionResetError, OSError) as e:
        raise ConnectionLost(str(e)) from e


def _wait_writable(sock: socket.socket, timeout: float) -> None:
    # NOT select.select: it raises ValueError for fds >= FD_SETSIZE
    # (1024) — exactly the many-connection regime the hub enables.
    import selectors as _selectors

    sel = _selectors.DefaultSelector()
    try:
        sel.register(sock, _selectors.EVENT_WRITE)
        sel.select(timeout)
    finally:
        sel.close()


def _sendall_vectored(sock: socket.socket, buffers: list) -> None:
    views = [memoryview(b).cast("B") for b in buffers if len(b)]
    while views:
        try:
            sent = sock.sendmsg(views)
        except (BlockingIOError, InterruptedError):
            # Hub-registered sockets are non-blocking; senders run on
            # ordinary threads and may wait for writability.
            _wait_writable(sock, 5.0)
            continue
        while sent > 0 and views:
            head = views[0]
            if sent >= len(head):
                sent -= len(head)
                views.pop(0)
            else:
                views[0] = head[sent:]
                sent = 0


def recv_msg(sock: socket.socket, key: bytes) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size + _DIGEST_BYTES)
    if header is None:
        return None
    (length,) = _LEN.unpack(header[: _LEN.size])
    digest = bytes(header[_LEN.size:])
    if length > _MAX_FRAME:  # enforced before buffering anything
        return None
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    if _frame_mac(sock):
        expect = _hmac.new(key, payload, hashlib.sha256).digest()
        if not _hmac.compare_digest(digest, expect):
            # Unauthenticated frame: never reaches the decoder.
            return None
    try:
        return decode_frame(payload)
    except Exception:
        # Malformed or wrong-version frame from an authenticated peer
        # (should have been caught at handshake): kill the connection.
        return None


def _recv_exact(sock: socket.socket, n: int):
    """Receive exactly n bytes into one preallocated buffer (the
    recv-append-join loop this replaces copied every chunk twice)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], min(n - got, 1 << 20))
        except (ConnectionResetError, OSError):
            return None
        if r == 0:
            return None
        got += r
    return buf


# ---------------------------------------------------------------------------
# selector hub: many sockets, one reader thread
# ---------------------------------------------------------------------------

class SelectorHub:
    """One epoll/kqueue thread multiplexing every registered RPC
    socket (reference: the asio event loop under every reference
    server, src/ray/common/asio/ — a thread per connection collapses
    at the 10k-actor scale: ~20k parked reader threads in the head +
    driver processes turn the scheduler into the bottleneck long
    before the protocol does).

    Frames are assembled incrementally per socket; complete frames go
    to the socket's `on_frame` callback ON THE HUB THREAD — callbacks
    must not block (both the server and client layers immediately
    hand off to executors / queues). EOF or socket error fires
    `on_close` once and unregisters."""

    def __init__(self, name: str = "rpc-hub"):
        import selectors

        self._selector = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._selector.register(
            self._wake_r, selectors.EVENT_READ, None
        )
        self._lock = threading.Lock()
        self._pending_ops: List[tuple] = []
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    def register(self, sock, key, mac, on_frame, on_close) -> None:
        sock.setblocking(False)
        state = _SockState(sock, key, mac, on_frame, on_close)
        with self._lock:
            self._pending_ops.append(("add", sock, state))
        self._wake()

    def unregister(self, sock) -> None:
        with self._lock:
            self._pending_ops.append(("del", sock, None))
        self._wake()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    def _apply_ops(self) -> None:
        import selectors

        with self._lock:
            ops, self._pending_ops = self._pending_ops, []
        for op, sock, state in ops:
            try:
                if op == "add":
                    try:
                        self._selector.register(
                            sock, selectors.EVENT_READ, state
                        )
                    except KeyError:
                        # fd reuse: a closed socket's entry still maps
                        # this fd (the owner closed without
                        # unregistering; epoll dropped it silently).
                        # Evict the stale entry or the NEW connection
                        # would be permanently deaf.
                        stale = self._selector.get_map().get(
                            sock.fileno()
                        )
                        if stale is not None:
                            self._selector.unregister(stale.fileobj)
                        self._selector.register(
                            sock, selectors.EVENT_READ, state
                        )
                else:
                    self._selector.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass

    def _loop(self) -> None:
        while not self._closed:
            self._apply_ops()
            try:
                events = self._selector.select(timeout=1.0)
            except OSError:
                # A registered socket was closed by its owner without
                # unregistering: the selector raises EBADF on every
                # select. Sweep out dead fds (and fire their on_close)
                # or this loop would spin forever serving nobody.
                self._sweep_dead()
                continue
            for sel_key, _ in events:
                if sel_key.fd == self._wake_r:
                    try:
                        while os.read(self._wake_r, 4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                state: _SockState = sel_key.data
                if state is not None:
                    self._service(state)

    def _sweep_dead(self) -> None:
        for sel_key in list(self._selector.get_map().values()):
            sock = sel_key.fileobj
            if sock == self._wake_r:
                continue
            dead = False
            try:
                dead = sock.fileno() < 0
            except Exception:
                dead = True
            if dead:
                try:
                    self._selector.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
                state = sel_key.data
                if state is not None and not state.closed:
                    state.closed = True
                    try:
                        state.on_close()
                    except Exception:
                        pass

    def _service(self, state: "_SockState") -> None:
        closed = False
        try:
            while True:
                chunk = state.sock.recv(1 << 20)
                if not chunk:
                    closed = True
                    break
                state.buf += chunk
                # Over-greedy reads starve other sockets; parse what
                # we have and come back on the next readiness event.
                if len(state.buf) >= (16 << 20):
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            closed = True
        self._drain_frames(state)
        if closed:
            try:
                self._selector.unregister(state.sock)
            except (KeyError, ValueError, OSError):
                pass
            if not state.closed:
                state.closed = True
                try:
                    state.on_close()
                except Exception:
                    pass

    def _kill(self, state: "_SockState") -> None:
        state.buf = bytearray()
        try:
            self._selector.unregister(state.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            state.sock.close()
        except OSError:
            pass
        if not state.closed:
            state.closed = True
            try:
                state.on_close()
            except Exception:
                pass

    def _drain_frames(self, state: "_SockState") -> None:
        header_len = _LEN.size + _DIGEST_BYTES
        buf = state.buf
        offset = 0  # consume via offset; reslicing per frame is O(n^2)
        try:
            while True:
                if len(buf) - offset < header_len:
                    return
                (length,) = _LEN.unpack_from(buf, offset)
                if length > _MAX_FRAME:
                    offset = 0
                    self._kill(state)  # poisoned peer: drop it
                    return
                total = header_len + length
                if len(buf) - offset < total:
                    return
                digest = bytes(
                    buf[offset + _LEN.size:offset + header_len]
                )
                payload = bytes(
                    buf[offset + header_len:offset + total]
                )
                offset += total
                if state.mac:
                    expect = _hmac.new(
                        state.key, payload, hashlib.sha256
                    ).digest()
                    if not _hmac.compare_digest(digest, expect):
                        # Unauthenticated frame: terminate the
                        # connection (module-docstring invariant;
                        # matches recv_msg).
                        offset = 0
                        self._kill(state)
                        return
                try:
                    msg = decode_frame(payload)
                except Exception:
                    offset = 0
                    self._kill(state)
                    return
                if msg is None:
                    continue
                try:
                    state.on_frame(msg)
                except Exception:
                    pass
        finally:
            if offset and state.buf is buf:
                del buf[:offset]  # single compaction per drain

    def close(self) -> None:
        self._closed = True
        self._wake()


class _SockState:
    __slots__ = ("sock", "key", "mac", "on_frame", "on_close", "buf",
                 "closed")

    def __init__(self, sock, key, mac, on_frame, on_close):
        self.sock = sock
        self.key = key
        self.mac = mac
        self.on_frame = on_frame
        self.on_close = on_close
        self.buf = bytearray()
        self.closed = False


_hub_lock = threading.Lock()  # rt: noqa[RT004] — the hub it guards is created lazily per process, post-fork
_process_hub: Optional[SelectorHub] = None
_client_pool = None


def _reset_rpc_globals_after_fork() -> None:
    """Forked children inherit the hub/pool OBJECTS but not their
    threads; reset so the child lazily builds fresh ones."""
    global _process_hub, _client_pool
    _process_hub = None
    _client_pool = None


os.register_at_fork(after_in_child=_reset_rpc_globals_after_fork)


def _client_executor():
    """Shared pool draining client-side pushes/async callbacks (they
    may block; the hub thread must not)."""
    global _client_pool
    with _hub_lock:
        if _client_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _client_pool = ThreadPoolExecutor(
                max_workers=int(
                    os.environ.get("RT_RPC_CLIENT_POOL_THREADS", "8")
                ),
                thread_name_prefix="rpc-client-pool",
            )
        return _client_pool


def process_hub() -> SelectorHub:
    """Process-wide hub shared by every RpcClient and RpcServer in
    this process (daemons, drivers, and workers alike)."""
    global _process_hub
    with _hub_lock:
        if _process_hub is None or _process_hub._closed:
            _process_hub = SelectorHub()
        # Forked children inherit the parent's hub OBJECT but not its
        # thread: detect and rebuild (worker fork-server children).
        if not _process_hub._thread.is_alive():
            _process_hub = SelectorHub()
        return _process_hub


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class RpcServer:
    """Threaded socket server dispatching named methods over any mix
    of Unix-domain and TCP listeners (reference: one gRPC server
    serving NodeManagerService on a port, grpc_server.h).

    Handlers run on per-connection reader threads; a handler may reply
    synchronously (return a dict) or later via the provided
    `Connection.push` / deferred reply handle.
    """

    def __init__(self, address: str, auth_key: Optional[bytes] = None):
        self.auth_key = auth_key or default_auth_key()
        self._handlers: Dict[str, Callable] = {}
        #: Methods whose handlers run INLINE on the hub thread instead
        #: of hopping through the connection queue + executor pool.
        #: Only for handlers that never block (a queue.put): the task
        #: hot path pays one thread wakeup, not two. Inline frames
        #: preserve arrival order with each other; a connection mixing
        #: inline and pooled methods loses cross-kind ordering, so
        #: only register methods whose senders don't rely on it.
        self._inline_handlers: Dict[str, Callable] = {}
        self._connections: Dict[int, "Connection"] = {}
        self._conn_counter = 0
        self._lock = threading.Lock()
        self._closed = False
        self._started = False
        self._listeners: List[tuple] = []  # (sock, canonical_addr)
        self._unix_paths: List[str] = []
        self._accept_threads: List[threading.Thread] = []
        self.address = self.add_listener(address)

    def add_listener(
        self, address: str, advertise_host: Optional[str] = None
    ) -> str:
        """Bind an additional address; returns its canonical form
        (ephemeral port resolved, wildcard bind host replaced by an
        address other hosts can actually dial)."""
        parsed = parse_address(address)
        if parsed[0] == "unix":
            path = parsed[1]
            if os.path.exists(path):
                os.unlink(path)
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            # The kernel's file permissions ARE the auth layer on
            # unix sockets (per-frame MACs are TCP-only, see
            # _frame_mac): owner-only on both the socket and its
            # directory, independent of the process umask.
            old_umask = os.umask(0o077)
            try:
                sock.bind(path)
            finally:
                os.umask(old_umask)
            try:
                os.chmod(path, 0o600)
                os.chmod(os.path.dirname(path) or ".", 0o700)
            except OSError:
                pass
            canonical = path
            self._unix_paths.append(path)
        else:
            _, host, port = parsed
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host or "0.0.0.0", port))
            bound_port = sock.getsockname()[1]
            adv = advertise_host or host
            if not adv or adv in ("0.0.0.0", "::"):
                # Wildcard binds must advertise a dialable address.
                adv = _detect_host_ip()
            canonical = f"tcp://{adv}:{bound_port}"
        sock.listen(128)
        self._listeners.append((sock, canonical))
        thread = threading.Thread(
            target=self._accept_loop,
            args=(sock,),
            name=f"rpc-accept:{canonical}",
            daemon=True,
        )
        self._accept_threads.append(thread)
        if self._started:
            thread.start()  # server already running: serve immediately
        return canonical

    def register(
        self, method: str, handler: Callable, inline: bool = False
    ) -> None:
        self._handlers[method] = handler
        if inline:
            self._inline_handlers[method] = handler

    def start(self) -> None:
        self._started = True
        for thread in self._accept_threads:
            if not thread.is_alive():
                thread.start()

    def _accept_loop(self, listener: socket.socket) -> None:
        while not self._closed:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            with self._lock:
                self._conn_counter += 1
                conn = Connection(self, sock, self._conn_counter)
                self._connections[conn.conn_id] = conn
            # Handshake + hub registration; no thread per connection
            # (SelectorHub reads all of them, handlers run on the
            # server's bounded pool with per-connection ordering).
            conn.start()

    def _get_executor(self):
        with self._lock:
            if getattr(self, "_executor", None) is None:
                from concurrent.futures import ThreadPoolExecutor

                self._executor = ThreadPoolExecutor(
                    max_workers=int(
                        os.environ.get("RT_RPC_POOL_THREADS", "32")
                    ),
                    thread_name_prefix="rpc-pool",
                )
            return self._executor

    def _dispatch(
        self, conn: "Connection", msg: dict, t_enq: float = 0.0
    ) -> None:
        method = msg.get("_method", "")
        mid = msg.get("_mid")
        handler = self._handlers.get(method)
        if handler is None:
            if mid:
                conn.reply(mid, {"_error": f"no such method: {method}"})
            return
        t_start = time.monotonic()
        queue_s = (t_start - t_enq) if t_enq else 0.0
        if not _schema_known(method) and method not in _schemaless_warned:
            # Once per process per method: a served-but-unschema'd
            # method skips typed validation entirely — always a
            # framework bug (wire.SCHEMAS describes our own plane),
            # caught statically by `ray_tpu check` RT104 but made
            # loud here too for out-of-tree handlers.
            _schemaless_warned.add(method)
            import sys as _sys

            print(
                f"[rpc] method {method!r} is served without a "
                "wire.SCHEMAS entry; arguments are not validated "
                "(add a schema — see ray_tpu check RT104)",
                file=_sys.stderr,
            )
        # Typed argument validation (wire.SCHEMAS): malformed frames
        # get a clean schema error instead of a KeyError mid-handler.
        schema_err = _schema_validate(method, msg)
        if schema_err is not None:
            if mid:
                conn.reply(
                    mid, {"_error": f"schema violation: {schema_err}"}
                )
            else:
                # A dropped NOTIFY is invisible to the sender — always
                # a framework bug (schemas describe our own senders),
                # so make it loud instead of wedging silently.
                import sys as _sys

                print(
                    f"[rpc] dropping notify with schema violation: "
                    f"{schema_err}",
                    file=_sys.stderr,
                )
            return
        try:
            result = handler(conn, msg)
        except Exception as e:  # noqa: BLE001 — errors propagate to caller
            import traceback

            exec_s = time.monotonic() - t_start
            _event_stats().record(method, queue_s, exec_s, error=True)
            _flight().record(
                "rpc.server",
                method,
                exec_s * 1e3,
                {"queue_ms": round(queue_s * 1e3, 3), "error": True},
            )
            if mid:
                conn.reply(
                    mid, {"_error": f"{e}\n{traceback.format_exc()}"}
                )
            return
        exec_s = time.monotonic() - t_start
        _event_stats().record(method, queue_s, exec_s)
        _flight().record(
            "rpc.server",
            method,
            exec_s * 1e3,
            {"queue_ms": round(queue_s * 1e3, 3)} if queue_s else None,
        )
        if result is not DEFERRED and mid:
            conn.reply(mid, result or {})

    def _on_disconnect(self, conn: "Connection") -> None:
        with self._lock:
            self._connections.pop(conn.conn_id, None)
        handler = self._handlers.get("_disconnect")
        if handler is not None:
            try:
                handler(conn, {})
            except Exception:
                pass

    def connections(self) -> list:
        with self._lock:
            return list(self._connections.values())

    def close(self) -> None:
        self._closed = True
        for sock, _ in self._listeners:
            # shutdown() first: close() alone does not release a
            # listening port while an accept thread is blocked on it
            # (the in-flight accept pins the open file description, so
            # the port stays in LISTEN and a restarted server cannot
            # rebind it).
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for thread in self._accept_threads:
            if thread.is_alive() and thread is not threading.current_thread():
                thread.join(timeout=1.0)
        for conn in self.connections():
            conn.close()
        for path in self._unix_paths:
            if os.path.exists(path):
                try:
                    os.unlink(path)
                except OSError:
                    pass


#: Sentinel a handler returns to indicate it will reply later via
#: `Connection.reply(mid, ...)` (used for blocking ops like object gets).
DEFERRED = object()

#: Methods already warned about for missing wire schemas (once per
#: process; set.add is GIL-atomic, a duplicate warning on a race is
#: harmless).
_schemaless_warned: set = set()


class Connection:
    """Server-side view of one client connection.

    Frames arrive via the process SelectorHub; handlers run on the
    server's bounded pool with PER-CONNECTION ordering (one drain task
    at a time walks this connection's queue) — the property the
    protocol relies on (e.g. a create_actor notify is processed before
    the same driver's first method submit)."""

    _DISCONNECT = object()

    def __init__(self, server: RpcServer, sock: socket.socket, conn_id: int):
        self._server = server
        self._sock = sock
        self.conn_id = conn_id
        self._send_lock = threading.Lock()
        self._key = server.auth_key  # replaced by the conn key in start
        self.metadata: Dict[str, Any] = {}  # e.g. worker id after register
        self._queue: deque = deque()
        self._queue_lock = threading.Lock()
        self._draining = False

    def start(self) -> None:
        # Nonce handshake: frames on this connection are keyed by
        # HMAC(cluster_key, nonce), so a frame recorded on another
        # connection can't be replayed here. The trailing byte carries
        # the protocol version (reference: versioned proto schemas) —
        # mismatched peers fail at connect with a clear error.
        nonce = os.urandom(16)
        hello = nonce + bytes([PROTOCOL_VERSION])
        try:
            with self._send_lock:
                self._sock.sendall(_LEN.pack(len(hello)) + hello)
        except OSError:
            self._server._on_disconnect(self)
            return
        self._key = _connection_key(self._server.auth_key, nonce)
        process_hub().register(
            self._sock,
            self._key,
            _frame_mac(self._sock),
            self._on_frame,
            self._on_close,
        )

    # -- hub callbacks (hub thread: enqueue only, never block) --------
    def _on_frame(self, msg: dict) -> None:
        method = msg.get("_method", "")
        inline = self._server._inline_handlers.get(method)
        if inline is not None:
            # Hot path (e.g. execute_tasks -> task_queue.put): the
            # handler is non-blocking by contract, so it runs right
            # here and the frame skips the queue + pool wakeup. Runs
            # AHEAD of any still-queued pooled frames — inline methods
            # are registered only where that reordering is harmless.
            # Telemetry parity with _dispatch: the hottest RPC in the
            # system must not vanish from event stats / the flight
            # recorder just because it dispatches inline.
            err = _schema_validate(method, msg)
            mid = msg.get("_mid")
            if err is not None:
                if mid:
                    self.reply(mid, {"_error": f"schema violation: {err}"})
                return
            t0 = time.monotonic()
            try:
                result = inline(self, msg)
            except Exception as e:  # noqa: BLE001 — to caller
                import traceback

                exec_s = time.monotonic() - t0
                _event_stats().record(method, 0.0, exec_s, error=True)
                _flight().record(
                    "rpc.server", method, exec_s * 1e3, {"error": True}
                )
                if mid:
                    self.reply(
                        mid,
                        {"_error": f"{e}\n{traceback.format_exc()}"},
                    )
                return
            exec_s = time.monotonic() - t0
            _event_stats().record(method, 0.0, exec_s)
            _flight().record("rpc.server", method, exec_s * 1e3)
            if result is not DEFERRED and mid:
                self.reply(mid, result or {})
            return
        self._enqueue(msg)

    def _on_close(self) -> None:
        # Rides the same ordered queue so the disconnect handler runs
        # AFTER every frame that arrived before EOF.
        self._enqueue(self._DISCONNECT)

    def _enqueue(self, item) -> None:
        # The enqueue timestamp feeds per-handler queueing-delay stats
        # (event_stats.py — the asio loop-lag analog).
        with self._queue_lock:
            self._queue.append((item, time.monotonic()))
            if self._draining:
                return
            self._draining = True
        self._server._get_executor().submit(self._drain)

    def _drain(self) -> None:
        while True:
            with self._queue_lock:
                if not self._queue:
                    self._draining = False
                    return
                item, t_enq = self._queue.popleft()
            if item is self._DISCONNECT:
                self._server._on_disconnect(self)
                continue
            try:
                self._server._dispatch(self, item, t_enq)
            except Exception:
                pass

    def reply(self, mid, payload: dict) -> None:
        payload = dict(payload)
        payload["_mid"] = mid
        with self._send_lock:
            try:
                send_msg(self._sock, payload, self._key)
            except ConnectionLost:
                pass

    def push(self, channel: str, payload: dict) -> None:
        payload = dict(payload)
        payload["_mid"] = -1
        payload["_push"] = channel
        with self._send_lock:
            try:
                send_msg(self._sock, payload, self._key)
            except ConnectionLost:
                pass

    def close(self) -> None:
        try:
            process_hub().unregister(self._sock)
        except Exception:
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RpcClient:
    """Thread-safe client with correlation ids, pushes, and retries."""

    def __init__(
        self,
        path: str,
        push_handler: Optional[Callable[[str, dict], None]] = None,
        connect_timeout: float = 10.0,
        auth_key: Optional[bytes] = None,
        on_reconnect: Optional[Callable[[], None]] = None,
    ):
        self._path = path
        self._parsed = parse_address(path)
        self.auth_key = auth_key or default_auth_key()
        self._push_handler = push_handler
        #: Called (on the reconnecting thread, outside locks) after a
        #: successful reconnect — the server saw a brand-new connection,
        #: so per-connection server state (e.g. log subscriptions) must
        #: be re-established by the client.
        self._on_reconnect = on_reconnect
        self._mid = 0
        self._lock = threading.Lock()
        # Serializes whole frames: call()/notify() run on arbitrary
        # threads (ObjectRef.__del__ fires on GC threads) and an
        # interleaved sendall would corrupt the length-prefixed wire.
        # Also guards the (sock, conn_key) pair so a sender never mixes
        # one connection's socket with another's key.
        self._send_lock = threading.Lock()
        self._reconnect_lock = threading.Lock()
        self._pending: Dict[int, threading.Event] = {}
        #: mid -> callback for async calls (call_async); invoked on the
        #: reader thread with the reply dict, or with
        #: {"_error": "__connection_lost__"} if the connection dies.
        self._pending_cb: Dict[int, Callable[[dict], None]] = {}
        #: mid -> connection generation the request was SENT on (absent
        #: until the send completes). Reconnect only fails mids sent on
        #: an older generation; a request that slipped onto the new
        #: socket (or hasn't been sent yet) must not be flushed.
        self._pending_gen: Dict[int, int] = {}
        self._replies: Dict[int, dict] = {}
        self._closed = False
        #: Bumped on every (re)connect; stale reader threads check it
        #: before flushing waiters so a dead connection's teardown can't
        #: fail calls issued on its replacement.
        self._conn_gen = 0
        sock, key = self._connect(connect_timeout)
        #: Per-connection frame key, derived from the server's nonce in
        #: _connect (mirrors Connection.serve). Replaced on _reconnect.
        self._sock, self._conn_key = sock, key
        self._start_reader(sock, key, self._conn_gen)

    def set_on_reconnect(self, cb: Optional[Callable[[], None]]) -> None:
        self._on_reconnect = cb

    def _start_reader(self, sock, key, gen) -> None:
        """Register with the process SelectorHub (one epoll thread for
        every client in the process — a thread per client collapses at
        the 10k-direct-connection scale). Sync replies resolve inline
        on the hub thread (event.set, non-blocking); pushes and async
        callbacks drain through a per-client ORDERED queue on the
        shared client pool, preserving the old single-reader-thread
        ordering for a connection's pushes."""
        process_hub().register(
            sock,
            key,
            _frame_mac(sock),
            lambda msg: self._hub_frame(msg, gen),
            lambda: self._hub_closed(gen),
        )

    def _hub_frame(self, msg: dict, gen: int) -> None:
        mid = msg.get("_mid")
        if mid == -1:
            if self._push_handler is not None:
                self._enqueue_work(("push", msg))
            return
        partial = msg.get("_part")
        with self._lock:
            event = self._pending.pop(mid, None)
            if event is not None:
                self._replies[mid] = msg
            if partial:
                # Streamed partial reply (execute_tasks outcome
                # parts): the callback stays registered until the
                # final frame so it fires once per part.
                entry = self._pending_cb.get(mid)
            else:
                entry = self._pending_cb.pop(mid, None)
                if entry is not None:
                    self._pending_gen.pop(mid, None)
        if event is not None:
            event.set()
        if entry is not None:
            callback, inline = entry
            if inline:
                # Caller opted into hub-thread delivery (call_async
                # inline=True): the reply is handled with zero thread
                # hops. The callback must be near-non-blocking — the
                # batch submit path's window bounds any send it makes
                # to buffers the peer is actively draining.
                try:
                    callback(msg)
                except Exception:
                    pass
            else:
                self._enqueue_work(("cb", callback, msg))

    def _hub_closed(self, gen: int) -> None:
        # Connection lost: wake all waiters with an error — but only
        # if this registration still owns the live connection; a stale
        # socket's teardown must not fail calls issued on its
        # replacement.
        with self._lock:
            if gen != self._conn_gen:
                return
            for mid, event in self._pending.items():
                self._replies[mid] = {"_error": "__connection_lost__"}
                event.set()
            self._pending.clear()
            self._pending_gen.clear()
            callbacks = [cb for cb, _inline in self._pending_cb.values()]
            self._pending_cb.clear()
        for callback in callbacks:
            self._enqueue_work(
                ("cb", callback, {"_error": "__connection_lost__"})
            )

    def _enqueue_work(self, item) -> None:
        with self._lock:
            queue = getattr(self, "_work_queue", None)
            if queue is None:
                queue = self._work_queue = deque()
                self._work_draining = False
            queue.append(item)
            if self._work_draining:
                return
            self._work_draining = True
        _client_executor().submit(self._drain_work)

    def _drain_work(self) -> None:
        while True:
            with self._lock:
                if not self._work_queue:
                    self._work_draining = False
                    return
                item = self._work_queue.popleft()
            try:
                if item[0] == "push":
                    self._push_handler(item[1].get("_push", ""), item[1])
                else:
                    item[1](item[2])
            except Exception:
                pass

    def _connect(self, timeout: float) -> Tuple[socket.socket, bytes]:
        deadline = time.time() + timeout
        last_err: Exception | None = None
        while time.time() < deadline:
            if self._parsed[0] == "unix":
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                target: Any = self._parsed[1]
            else:
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                target = (self._parsed[1], self._parsed[2])
            try:
                sock.connect(target)
                # Client half of the nonce handshake (see module
                # docstring / Connection.serve): read [8-byte len]
                # [16-byte nonce][1-byte protocol version], verify the
                # version, and key every subsequent frame on this
                # socket with HMAC(cluster_key, "rt-conn"||nonce).
                prev_timeout = sock.gettimeout()
                sock.settimeout(max(deadline - time.time(), 1.0))
                header = _recv_exact(sock, _LEN.size)
                if header is None:
                    raise ConnectionResetError("no nonce from server")
                (nlen,) = _LEN.unpack(header)
                if nlen < 17 or nlen > 64:
                    raise ConnectionResetError(
                        f"bad hello length {nlen} from server "
                        "(pre-versioning peer?)"
                    )
                hello = _recv_exact(sock, nlen)
                if hello is None:
                    raise ConnectionResetError("truncated hello")
                nonce, version = hello[:16], hello[16]
                if version != PROTOCOL_VERSION:
                    sock.close()
                    # RpcError (not the wire-level ProtocolVersionError)
                    # so every existing `except RpcError` boundary in
                    # the daemons handles the mismatch cleanly instead
                    # of dying on an unexpected exception type; the
                    # non-OSError type also breaks out of the connect
                    # retry loop immediately.
                    raise RpcError(
                        f"protocol version mismatch: server speaks "
                        f"v{version}, this client speaks "
                        f"v{PROTOCOL_VERSION}"
                    )
                sock.settimeout(prev_timeout)
                return sock, _connection_key(self.auth_key, nonce)
            except (
                FileNotFoundError,
                ConnectionRefusedError,
                ConnectionResetError,
                TimeoutError,
                OSError,
            ) as e:
                last_err = e
                sock.close()
                time.sleep(0.05)
        raise ConnectionLost(f"cannot connect to {self._path}: {last_err}")

    def call(
        self,
        method: str,
        timeout: Optional[float] = None,
        retries: int = 0,
        **kwargs,
    ) -> dict:
        """Synchronous call; raises RpcError on handler error."""
        attempt = 0
        backoff = 0.1
        while True:
            with self._lock:
                seen_gen = self._conn_gen
            if _chaos_should_fail(method):
                reply = {"_error": "__chaos_injected_failure__"}
            else:
                reply = self._call_once(method, timeout, kwargs)
            err = reply.get("_error")
            if err is None:
                return reply
            if attempt < retries and err in (
                "__chaos_injected_failure__",
                "__connection_lost__",
                "__timeout__",
            ):
                attempt += 1
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                if err == "__connection_lost__":
                    self._reconnect(seen_gen)
                continue
            raise RpcError(f"{method}: {err}")

    def _call_once(self, method, timeout, kwargs) -> dict:
        # Dynamic RT203: convict any caller that reaches a synchronous
        # RPC while holding a witness-instrumented lock (one module-
        # global read when the witness is off).
        _note_blocking(f"rpc.call:{method}")
        rec = _flight()
        if rec.enabled:
            t0 = time.monotonic()
            reply = self._call_once_inner(method, timeout, kwargs)
            err = reply.get("_error")
            rec.record(
                "rpc.client",
                method,
                (time.monotonic() - t0) * 1e3,
                {"error": True} if err is not None else None,
            )
            return reply
        return self._call_once_inner(method, timeout, kwargs)

    def _call_once_inner(self, method, timeout, kwargs) -> dict:
        with self._lock:
            if self._closed:
                return {"_error": "__connection_lost__"}
            self._mid += 1
            mid = self._mid
            event = threading.Event()
            self._pending[mid] = event
        msg = dict(kwargs)
        msg["_method"] = method
        msg["_mid"] = mid
        try:
            with self._send_lock:
                send_msg(self._sock, msg, self._conn_key)
                with self._lock:  # lock order: _send_lock then _lock
                    if mid in self._pending:
                        self._pending_gen[mid] = self._conn_gen
        except ConnectionLost:
            with self._lock:
                self._pending.pop(mid, None)
                self._pending_gen.pop(mid, None)
            return {"_error": "__connection_lost__"}
        if not event.wait(timeout=timeout):
            with self._lock:
                self._pending.pop(mid, None)
                self._pending_gen.pop(mid, None)
                # The reader may have raced the timeout and already
                # moved the reply into _replies; drop it or it leaks.
                self._replies.pop(mid, None)
            return {"_error": "__timeout__"}
        with self._lock:
            self._pending_gen.pop(mid, None)
            return self._replies.pop(mid)

    def call_async(
        self,
        method: str,
        callback: Callable[[dict], None],
        inline: bool = False,
        **kwargs,
    ) -> None:
        """Fire a request and invoke `callback(reply)` on the reader
        thread when the response arrives (or with
        ``{"_error": "__connection_lost__"}`` on connection loss). The
        hot path of the direct task transport: no per-call thread
        handoff on the send side. ``inline=True`` additionally invokes
        the callback straight on the hub thread (zero handoffs on the
        reply side) — only for near-non-blocking callbacks."""
        if _chaos_should_fail(method):
            # Same contract as a send failure: the callback fires
            # synchronously on the caller's thread (callers already
            # handle that for the closed-client path).
            callback({"_error": "__chaos_injected_failure__"})
            return
        rec = _flight()
        if rec.enabled:
            t0 = time.monotonic()
            inner = callback

            def callback(reply, _inner=inner, _t0=t0):  # noqa: F811
                rec.record(
                    "rpc.client",
                    method,
                    (time.monotonic() - _t0) * 1e3,
                    {"error": True}
                    if reply.get("_error") is not None
                    else None,
                )
                _inner(reply)
        with self._lock:
            if self._closed:
                callback({"_error": "__connection_lost__"})
                return
            self._mid += 1
            mid = self._mid
            self._pending_cb[mid] = (callback, inline)
        msg = dict(kwargs)
        msg["_method"] = method
        msg["_mid"] = mid
        try:
            with self._send_lock:
                send_msg(self._sock, msg, self._conn_key)
                with self._lock:
                    if mid in self._pending_cb:
                        self._pending_gen[mid] = self._conn_gen
        except ConnectionLost:
            with self._lock:
                dead = self._pending_cb.pop(mid, None)
                self._pending_gen.pop(mid, None)
            if dead is not None:
                dead[0]({"_error": "__connection_lost__"})

    def notify(self, method: str, **kwargs) -> None:
        """Fire-and-forget message (no reply expected)."""
        msg = dict(kwargs)
        msg["_method"] = method
        msg["_mid"] = 0
        try:
            with self._send_lock:
                send_msg(self._sock, msg, self._conn_key)
        except ConnectionLost:
            pass

    def _reconnect(self, seen_gen: Optional[int] = None) -> None:
        """Replace the connection. `seen_gen` is the generation the
        caller observed failing; if another thread already reconnected
        past it, this is a no-op (two racing retries produce one new
        connection, not two)."""
        reconnected = False
        with self._reconnect_lock:
            with self._lock:
                if self._closed:
                    return
                if seen_gen is not None and self._conn_gen != seen_gen:
                    return  # somebody else already reconnected
            # Unregister BEFORE close: epoll forgets a closed fd
            # silently, but the selectors bookkeeping would keep the
            # stale entry and make the replacement socket (which
            # typically reuses the same fd) fail to register.
            try:
                process_hub().unregister(self._sock)
            except Exception:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            sock, key = self._connect(10.0)  # rt: noqa[RT203] — _reconnect_lock intentionally serializes reconnect attempts, backoff included
            # Swap + generation bump + flush as one atomic step under
            # _send_lock: senders record their send generation while
            # holding it, so nothing can send during the swap and every
            # pending mid has an accurate generation tag.
            with self._send_lock:
                with self._lock:
                    if self._closed:  # close() raced the reconnect
                        try:
                            sock.close()
                        except OSError:
                            pass
                        return
                    self._sock, self._conn_key = sock, key
                    self._conn_gen += 1
                    gen = self._conn_gen
                    # Fail calls sent on a dead connection — they can
                    # never be answered here. Unsent/new-gen mids stay.
                    stale = [
                        mid for mid, g in self._pending_gen.items()
                        if g < gen and mid in self._pending
                    ]
                    for mid in stale:
                        event = self._pending.pop(mid)
                        self._pending_gen.pop(mid, None)
                        self._replies[mid] = {
                            "_error": "__connection_lost__"
                        }
                        event.set()
                    # Same for async-callback requests: their promised
                    # connection_lost error must fire or the caller's
                    # in-flight accounting wedges.
                    stale_cbs = []
                    for mid, g in list(self._pending_gen.items()):
                        if g < gen and mid in self._pending_cb:
                            stale_cbs.append(self._pending_cb.pop(mid)[0])
                            self._pending_gen.pop(mid, None)
            for cb in stale_cbs:
                try:
                    cb({"_error": "__connection_lost__"})
                except Exception:
                    pass
            self._start_reader(sock, key, gen)
            reconnected = True
        # Outside _reconnect_lock: a callback that triggers another
        # reconnect (its call() hits a dying fresh connection) must not
        # self-deadlock on the non-reentrant lock.
        if reconnected and self._on_reconnect is not None:
            try:
                self._on_reconnect()
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            process_hub().unregister(self._sock)
        except Exception:
            pass
        # Unregistering suppresses the hub's on_close, so flush
        # blocked call(timeout=None) waiters here — the removed
        # per-client reader thread used to do this when its recv
        # failed.
        try:
            self._hub_closed(self._conn_gen)
        except Exception:
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
