"""In-process on-demand profilers.

Reference: python/ray/dashboard/modules/reporter/profile_manager.py —
the dashboard attaches py-spy (CPU stacks / flamegraph) or memray
(allocations) to a live worker on demand. Neither tool ships in this
environment, and both need ptrace or an injected allocator; the
TPU-native rebuild profiles from INSIDE the worker instead — every
worker already runs an RPC server, so the profilers are pure-Python
handlers over interpreter introspection:

  cpu    — wall-clock stack sampler over sys._current_frames at a
           fixed rate; emits collapsed/folded stacks ("a;b;c N"), the
           flamegraph.pl / speedscope interchange format py-spy's
           --format raw produces.
  memory — tracemalloc window: top allocation sites grouped by
           traceback between start and stop.
  stack  — one immediate dump of every thread's Python stack
           (py-spy dump equivalent).

In-process sampling observes only Python frames (a thread stuck in C
shows its last Python frame — same blind spot py-spy --native=false
has) and costs nothing while not attached.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Dict, List, Optional


def dump_stacks() -> str:
    """All threads' current Python stacks as text."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(
            f"--- thread {ident} ({names.get(ident, '?')}) ---"
        )
        out.extend(
            line.rstrip()
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


def _folded(frame) -> str:
    """One sampled stack, root-first, flamegraph-collapsed."""
    parts: List[str] = []
    while frame is not None:
        code = frame.f_code
        parts.append(
            f"{code.co_name} "
            f"({code.co_filename.rsplit('/', 1)[-1]}"
            f":{frame.f_lineno})"
        )
        frame = frame.f_back
    return ";".join(reversed(parts))


def sample_cpu(
    duration_s: float = 5.0,
    hz: float = 100.0,
    exclude_thread: Optional[int] = None,
) -> dict:
    """Sample all threads for `duration_s` at `hz`.

    Returns {"folded": "stack N\n...", "samples": n, "threads": k}.
    The sampler thread excludes itself (and optionally the caller's
    RPC thread) so the profile shows the profilee, not the profiler.
    """
    duration_s = min(float(duration_s), 120.0)
    interval = 1.0 / max(1.0, min(float(hz), 1000.0))
    me = threading.get_ident()
    counts: Counter = Counter()
    threads_seen: set = set()
    samples = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me or ident == exclude_thread:
                continue
            threads_seen.add(ident)
            counts[_folded(frame)] += 1
        samples += 1
        time.sleep(interval)
    folded = "\n".join(
        f"{stack} {n}" for stack, n in counts.most_common()
    )
    return {
        "folded": folded,
        "samples": samples,
        "threads": len(threads_seen),
        "duration_s": duration_s,
        "hz": hz,
    }


def profile_memory(duration_s: float = 5.0, top: int = 20) -> dict:
    """tracemalloc window: allocations between start and stop,
    grouped by allocation site, biggest first."""
    import tracemalloc

    duration_s = min(float(duration_s), 120.0)
    was_tracing = tracemalloc.is_tracing()
    if not was_tracing:
        tracemalloc.start(10)
    try:
        before = tracemalloc.take_snapshot()
        time.sleep(duration_s)
        after = tracemalloc.take_snapshot()
    finally:
        if not was_tracing:
            tracemalloc.stop()
    stats = after.compare_to(before, "traceback")
    entries = []
    for stat in stats[: int(top)]:
        entries.append(
            {
                "size_diff_kb": round(stat.size_diff / 1024, 1),
                "count_diff": stat.count_diff,
                "traceback": stat.traceback.format(),
            }
        )
    current, peak = (
        tracemalloc.get_traced_memory()
        if tracemalloc.is_tracing()
        else (0, 0)
    )
    return {
        "top": entries,
        "traced_current_kb": round(current / 1024, 1),
        "traced_peak_kb": round(peak / 1024, 1),
        "duration_s": duration_s,
    }


#: RPC surface: kind -> handler(**params). Registered on the worker's
#: direct server and reachable through the daemon/head `profile_worker`
#: relay (dashboard /api/profile).
def run_profile(kind: str, **params) -> dict:
    if kind == "stack":
        return {"stacks": dump_stacks()}
    if kind == "cpu":
        return sample_cpu(
            duration_s=params.get("duration_s", 5.0),
            hz=params.get("hz", 100.0),
            exclude_thread=params.get("exclude_thread"),
        )
    if kind == "memory":
        return profile_memory(
            duration_s=params.get("duration_s", 5.0),
            top=params.get("top", 20),
        )
    raise ValueError(f"unknown profile kind: {kind!r}")
