"""In-process on-demand profilers.

Reference: python/ray/dashboard/modules/reporter/profile_manager.py —
the dashboard attaches py-spy (CPU stacks / flamegraph) or memray
(allocations) to a live worker on demand. Neither tool ships in this
environment, and both need ptrace or an injected allocator; the
TPU-native rebuild profiles from INSIDE the worker instead — every
worker already runs an RPC server, so the profilers are pure-Python
handlers over interpreter introspection:

  cpu    — wall-clock stack sampler over sys._current_frames at a
           fixed rate; emits collapsed/folded stacks ("a;b;c N"), the
           flamegraph.pl / speedscope interchange format py-spy's
           --format raw produces.
  memory — tracemalloc window: top allocation sites grouped by
           traceback between start and stop.
  stack  — one immediate dump of every thread's Python stack
           (py-spy dump equivalent).

In-process sampling observes only Python frames (a thread stuck in C
shows its last Python frame — same blind spot py-spy --native=false
has) and costs nothing while not attached.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Dict, List, Optional


def dump_stacks() -> str:
    """All threads' current Python stacks as text."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(
            f"--- thread {ident} ({names.get(ident, '?')}) ---"
        )
        out.extend(
            line.rstrip()
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


def _folded(frame) -> str:
    """One sampled stack, root-first, flamegraph-collapsed."""
    parts: List[str] = []
    while frame is not None:
        code = frame.f_code
        parts.append(
            f"{code.co_name} "
            f"({code.co_filename.rsplit('/', 1)[-1]}"
            f":{frame.f_lineno})"
        )
        frame = frame.f_back
    return ";".join(reversed(parts))


def sample_cpu(
    duration_s: float = 5.0,
    hz: float = 100.0,
    exclude_thread: Optional[int] = None,
) -> dict:
    """Sample all threads for `duration_s` at `hz`.

    Returns {"folded": "stack N\n...", "samples": n, "threads": k}.
    The sampler thread excludes itself (and optionally the caller's
    RPC thread) so the profile shows the profilee, not the profiler.
    """
    duration_s = min(float(duration_s), 120.0)
    interval = 1.0 / max(1.0, min(float(hz), 1000.0))
    me = threading.get_ident()
    counts: Counter = Counter()
    threads_seen: set = set()
    samples = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me or ident == exclude_thread:
                continue
            threads_seen.add(ident)
            counts[_folded(frame)] += 1
        samples += 1
        time.sleep(interval)
    folded = "\n".join(
        f"{stack} {n}" for stack, n in counts.most_common()
    )
    return {
        "folded": folded,
        "samples": samples,
        "threads": len(threads_seen),
        "duration_s": duration_s,
        "hz": hz,
    }


def profile_memory(duration_s: float = 5.0, top: int = 20) -> dict:
    """tracemalloc window: allocations between start and stop,
    grouped by allocation site, biggest first."""
    import tracemalloc

    duration_s = min(float(duration_s), 120.0)
    was_tracing = tracemalloc.is_tracing()
    if not was_tracing:
        tracemalloc.start(10)
    try:
        before = tracemalloc.take_snapshot()
        time.sleep(duration_s)
        after = tracemalloc.take_snapshot()
    finally:
        if not was_tracing:
            tracemalloc.stop()
    stats = after.compare_to(before, "traceback")
    entries = []
    for stat in stats[: int(top)]:
        entries.append(
            {
                "size_diff_kb": round(stat.size_diff / 1024, 1),
                "count_diff": stat.count_diff,
                "traceback": stat.traceback.format(),
            }
        )
    current, peak = (
        tracemalloc.get_traced_memory()
        if tracemalloc.is_tracing()
        else (0, 0)
    )
    return {
        "top": entries,
        "traced_current_kb": round(current / 1024, 1),
        "traced_peak_kb": round(peak / 1024, 1),
        "duration_s": duration_s,
    }


#: Cap on chrome-trace slices one timeline capture may emit (a 100 Hz
#: window over a thrashing thread churns slices; the merged gang
#: artifact must stay loadable).
_MAX_TIMELINE_EVENTS = 20000


def sample_timeline(
    duration_s: float = 2.0,
    hz: float = 100.0,
    start_at: Optional[float] = None,
) -> dict:
    """Wall-clock TIMELINE sampler: like `sample_cpu`, but instead of
    folding samples into counts it coalesces consecutive samples of
    one thread's leaf frame into chrome-trace 'X' slices on the
    UNIX-EPOCH-us clock — the shared clock every rank of a gang
    agrees on, which is what makes the merged gang profile line up.
    `start_at` (unix seconds) synchronizes the window start across
    ranks: the sampler sleeps until then before its first sample.
    Returns {"events", "folded", "samples", "threads", "t0", "t1"}.
    """
    duration_s = min(float(duration_s), 120.0)
    interval = 1.0 / max(1.0, min(float(hz), 1000.0))
    if start_at is not None:
        delay = float(start_at) - time.time()
        if delay > 0:
            time.sleep(min(delay, 30.0))
    me = threading.get_ident()
    counts: Counter = Counter()
    #: thread ident -> [slice_name, start_us, last_seen_us]
    open_slices: Dict[int, list] = {}
    events: List[dict] = []
    names = {t.ident: t.name for t in threading.enumerate()}

    def close(ident: int, now_us: float) -> None:
        entry = open_slices.pop(ident, None)
        if entry is None or len(events) >= _MAX_TIMELINE_EVENTS:
            return
        name, start_us, _last = entry
        events.append(
            {
                "name": name,
                "cat": "sample",
                "ph": "X",
                "ts": start_us,
                "dur": max(1.0, now_us - start_us),
                "pid": "profile",
                "tid": names.get(ident, f"thread {ident}"),
            }
        )

    samples = 0
    threads_seen: set = set()
    t0 = time.time()
    deadline = t0 + duration_s
    while time.time() < deadline:
        now_us = time.time() * 1e6
        frames = sys._current_frames()
        for ident in list(open_slices):
            if ident not in frames:
                close(ident, now_us)
        for ident, frame in frames.items():
            if ident == me:
                continue
            threads_seen.add(ident)
            if ident not in names:
                names[ident] = next(
                    (
                        t.name
                        for t in threading.enumerate()
                        if t.ident == ident
                    ),
                    f"thread {ident}",
                )
            code = frame.f_code
            leaf = (
                f"{code.co_name} "
                f"({code.co_filename.rsplit('/', 1)[-1]}"
                f":{frame.f_lineno})"
            )
            counts[_folded(frame)] += 1
            entry = open_slices.get(ident)
            if entry is not None and entry[0] == leaf:
                entry[2] = now_us
            else:
                if entry is not None:
                    close(ident, now_us)
                open_slices[ident] = [leaf, now_us, now_us]
        samples += 1
        time.sleep(interval)
    end_us = time.time() * 1e6
    for ident in list(open_slices):
        close(ident, end_us)
    return {
        "events": events,
        "folded": "\n".join(
            f"{stack} {n}" for stack, n in counts.most_common()
        ),
        "samples": samples,
        "threads": len(threads_seen),
        "duration_s": duration_s,
        "hz": hz,
        "t0": t0,
        "t1": end_us / 1e6,
    }


def capture_gang(
    duration_s: float = 2.0,
    hz: float = 100.0,
    start_at: Optional[float] = None,
) -> dict:
    """One rank's share of a coordinated gang-profile window. On TPU
    (and other accelerator) backends the window additionally runs
    under a `jax.profiler` trace whose artifact directory rides back
    in the result; everywhere else — and alongside it — the
    in-process timeline sampler provides the chrome-trace slices the
    head merges. jax is only touched when the process already
    imported it; failures degrade to sampler-only, never fail the
    capture."""
    import sys as _sys

    trace_dir = None
    profiler = None
    if "jax" in _sys.modules:
        try:
            import jax

            if jax.default_backend() != "cpu":
                import tempfile

                trace_dir = tempfile.mkdtemp(prefix="rt_gang_trace_")
                jax.profiler.start_trace(trace_dir)
                profiler = jax
        except Exception:  # noqa: BLE001 — sampler-only fallback
            trace_dir = None
            profiler = None
    try:
        result = sample_timeline(
            duration_s=duration_s, hz=hz, start_at=start_at
        )
    finally:
        if profiler is not None:
            try:
                profiler.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                trace_dir = None
    if trace_dir is not None:
        result["jax_trace_dir"] = trace_dir
    return result


#: RPC surface: kind -> handler(**params). Registered on the worker's
#: direct server and reachable through the daemon/head `profile_worker`
#: relay (dashboard /api/profile) — `gang` is the synchronized-window
#: capture rt.profile_gang fans out.
def run_profile(kind: str, **params) -> dict:
    if kind == "stack":
        return {"stacks": dump_stacks()}
    if kind == "cpu":
        return sample_cpu(
            duration_s=params.get("duration_s", 5.0),
            hz=params.get("hz", 100.0),
            exclude_thread=params.get("exclude_thread"),
        )
    if kind == "memory":
        return profile_memory(
            duration_s=params.get("duration_s", 5.0),
            top=params.get("top", 20),
        )
    if kind == "gang":
        return capture_gang(
            duration_s=params.get("duration_s", 2.0),
            hz=params.get("hz", 100.0),
            start_at=params.get("start_at"),
        )
    raise ValueError(f"unknown profile kind: {kind!r}")
