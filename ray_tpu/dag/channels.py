"""Shared-memory SPSC channels for compiled DAGs.

Reference: python/ray/experimental/channel/shared_memory_channel.py:159
— compiled graphs move data over mutable plasma buffers with
acquire/release semantics (core_worker/experimental_mutable_object_
manager.h:48) instead of per-call RPC. Here a channel is a POSIX
shared-memory ring buffer: single writer, single reader, length-framed
pickled records, monotonic head/tail counters in the segment header.
Same-host only by design — cross-host stage boundaries in a TPU
pipeline ride ICI/DCN collectives inside the jitted program
(parallel/pipeline), not the control-plane channel.
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

_HEADER = 16  # two u64 counters: head (written), tail (read)
_LEN = 8  # per-record length prefix

STOP = b"__RT_DAG_STOP__"


class ChannelClosedError(Exception):
    pass


class ChannelTimeoutError(Exception):
    pass


class ShmChannel:
    """Single-producer single-consumer shared-memory ring buffer."""

    def __init__(
        self,
        capacity: int = 4 * 1024 * 1024,
        *,
        name: Optional[str] = None,
        create: bool = True,
    ):
        self.capacity = capacity
        if create:
            self._shm = shared_memory.SharedMemory(
                create=True, size=_HEADER + capacity
            )
            self._shm.buf[:_HEADER] = b"\x00" * _HEADER
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # The creator owns the segment lifetime; stop the attaching
            # process's resource tracker from unlinking it at exit.
            try:
                resource_tracker.unregister(
                    self._shm._name, "shared_memory"  # noqa: SLF001
                )
            except Exception:
                pass
        self.name = self._shm.name
        self._closed = False

    # -- counters ------------------------------------------------------
    def _head(self) -> int:
        return struct.unpack_from("<Q", self._shm.buf, 0)[0]

    def _tail(self) -> int:
        return struct.unpack_from("<Q", self._shm.buf, 8)[0]

    def _set_head(self, v: int) -> None:
        struct.pack_into("<Q", self._shm.buf, 0, v)

    def _set_tail(self, v: int) -> None:
        struct.pack_into("<Q", self._shm.buf, 8, v)

    # -- ring IO -------------------------------------------------------
    def _write_at(self, pos: int, payload: bytes) -> None:
        offset = pos % self.capacity
        first = min(len(payload), self.capacity - offset)
        base = _HEADER + offset
        self._shm.buf[base : base + first] = payload[:first]
        if first < len(payload):
            rest = len(payload) - first
            self._shm.buf[_HEADER : _HEADER + rest] = payload[first:]

    def _read_at(self, pos: int, size: int) -> bytes:
        offset = pos % self.capacity
        first = min(size, self.capacity - offset)
        base = _HEADER + offset
        out = bytes(self._shm.buf[base : base + first])
        if first < size:
            out += bytes(self._shm.buf[_HEADER : _HEADER + size - first])
        return out

    # -- public --------------------------------------------------------
    def put_bytes(self, payload: bytes, timeout: Optional[float] = None):
        record = len(payload) + _LEN
        if record > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel "
                f"capacity {self.capacity}; recompile with a larger "
                "buffer_size_bytes"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.capacity - (self._head() - self._tail()) < record:
            if self._closed:
                raise ChannelClosedError(self.name)
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"put on {self.name}")
            time.sleep(0.0002)
        head = self._head()
        self._write_at(head, struct.pack("<Q", len(payload)))
        self._write_at(head + _LEN, payload)
        self._set_head(head + record)

    def get_bytes(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._head() - self._tail() < _LEN:
            if self._closed:
                raise ChannelClosedError(self.name)
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"get on {self.name}")
            time.sleep(0.0002)
        tail = self._tail()
        (size,) = struct.unpack("<Q", self._read_at(tail, _LEN))
        payload = self._read_at(tail + _LEN, size)
        self._set_tail(tail + _LEN + size)
        return payload

    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        self.put_bytes(pickle.dumps(value), timeout=timeout)

    def get(self, timeout: Optional[float] = None) -> Any:
        return pickle.loads(self.get_bytes(timeout=timeout))

    def close(self) -> None:
        self._closed = True
        try:
            self._shm.close()
        except BufferError:
            pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __reduce__(self):
        # Deserializing attaches to the same segment (reader side).
        return (_attach, (self.name, self.capacity))


def _attach(name: str, capacity: int) -> "ShmChannel":
    return ShmChannel(capacity, name=name, create=False)
