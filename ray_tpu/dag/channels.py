"""Shared-memory SPSC channels for compiled DAGs.

Reference: python/ray/experimental/channel/shared_memory_channel.py:159
— compiled graphs move data over mutable plasma buffers with
acquire/release semantics (core_worker/experimental_mutable_object_
manager.h:48) instead of per-call RPC. Here a channel is a POSIX
shared-memory ring buffer: single writer, single reader, length-framed
pickled records, monotonic head/tail counters in the segment header.
Same-host only by design — cross-host stage boundaries in a TPU
pipeline ride ICI/DCN collectives inside the jitted program
(parallel/pipeline), not the control-plane channel.
"""

from __future__ import annotations

import ctypes
import pickle
import struct
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

_HEADER = 24  # three u64s: head (written), tail (read), closed flag
_LEN = 8  # per-record length prefix


def _atomics():
    """(load_acquire, store_release) on u64 addresses, from the native
    library — real fences, correct on any architecture. Falls back to
    None (plain struct access, safe on x86-TSO where CPython's stores
    aren't reordered) when the toolchain is unavailable."""
    try:
        from .._native import load_library

        lib = load_library()
        if lib is not None and hasattr(lib, "rts_load_acq_u64"):
            return lib.rts_load_acq_u64, lib.rts_store_rel_u64
    except Exception:
        pass
    return None


_ATOMICS = _atomics()


def _futex():
    """(wait, wake) on the low u32 word of a counter, or None. The
    kernel-sleep half of the doorbell; spin covers the hot path."""
    try:
        from .._native import load_library

        lib = load_library()
        if lib is not None and hasattr(lib, "rts_futex_wait_u32"):
            return lib.rts_futex_wait_u32, lib.rts_futex_wake
    except Exception:
        pass
    return None


_FUTEX = _futex()
#: Hot-spin budget before sleeping in the kernel: covers the common
#: compiled-pipeline turnaround (~tens of us) without a syscall. On a
#: single-CPU machine spinning is counterproductive — the waiter burns
#: the exact quantum its peer needs to produce the data — so go
#: straight to the futex there.
import os as _os

_SPIN_NS = 100_000 if (_os.cpu_count() or 1) > 1 else 0
#: Bounded kernel waits so a peer's close() (shared flag, no doorbell
#: reachable after unmap) is noticed promptly even with no traffic.
_WAIT_CHUNK_NS = 20_000_000

STOP = b"__RT_DAG_STOP__"


class ChannelClosedError(Exception):
    pass


class ChannelTimeoutError(Exception):
    pass


class ShmChannel:
    """Single-producer single-consumer shared-memory ring buffer."""

    def __init__(
        self,
        capacity: int = 4 * 1024 * 1024,
        *,
        name: Optional[str] = None,
        create: bool = True,
    ):
        self.capacity = capacity
        if create:
            self._shm = shared_memory.SharedMemory(
                create=True, size=_HEADER + capacity
            )
            self._shm.buf[:_HEADER] = b"\x00" * _HEADER
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # The creator owns the segment lifetime; stop the attaching
            # process's resource tracker from unlinking it at exit.
            try:
                resource_tracker.unregister(
                    self._shm._name, "shared_memory"  # noqa: SLF001
                )
            except Exception:
                pass
        self.name = self._shm.name
        self._closed = False
        # Base address of the header for the native atomic accessors.
        self._base_addr = ctypes.addressof(
            ctypes.c_char.from_buffer(self._shm.buf)
        )
        # Guards counter access against close() unmapping the segment:
        # a native atomic load on an unmapped address is a segfault,
        # not an exception.
        self._io_lock = threading.Lock()

    # -- counters ------------------------------------------------------
    # head/tail publication follows the release/acquire pattern: the
    # writer stores payload bytes, then store-releases head; the reader
    # load-acquires head before reading the bytes (and symmetrically
    # for tail). With the native library absent this degrades to plain
    # accesses — safe on x86-TSO, where CPython emits no reordering.
    def _load(self, offset: int) -> int:
        with self._io_lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            if _ATOMICS is not None:
                return int(_ATOMICS[0](self._base_addr + offset))
            return struct.unpack_from("<Q", self._shm.buf, offset)[0]

    def _store(self, offset: int, v: int) -> None:
        with self._io_lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            if _ATOMICS is not None:
                _ATOMICS[1](self._base_addr + offset, v)
                return
            struct.pack_into("<Q", self._shm.buf, offset, v)

    def _head(self) -> int:
        return self._load(0)

    def _tail(self) -> int:
        return self._load(8)

    def _set_head(self, v: int) -> None:
        self._store(0, v)

    def _set_tail(self, v: int) -> None:
        self._store(8, v)

    def _shared_closed(self) -> bool:
        return self._load(16) != 0

    # -- ring IO -------------------------------------------------------
    def _write_at(self, pos: int, payload: bytes) -> None:
        offset = pos % self.capacity
        first = min(len(payload), self.capacity - offset)
        base = _HEADER + offset
        self._shm.buf[base : base + first] = payload[:first]
        if first < len(payload):
            rest = len(payload) - first
            self._shm.buf[_HEADER : _HEADER + rest] = payload[first:]

    def _read_at(self, pos: int, size: int) -> bytes:
        offset = pos % self.capacity
        first = min(size, self.capacity - offset)
        base = _HEADER + offset
        out = bytes(self._shm.buf[base : base + first])
        if first < size:
            out += bytes(self._shm.buf[_HEADER : _HEADER + size - first])
        return out

    # -- blocking ------------------------------------------------------
    def _await(self, cond, watch_offset: int, timeout, label: str):
        """Block until `cond()` holds. Adaptive: hot-spin for a short
        budget (covers the in-flight-producer case with zero
        syscalls), then sleep in the kernel on the counter at
        `watch_offset` via futex until the peer's doorbell — or
        sleep-poll when the native library is absent. The futex
        compares the counter's low u32 in-kernel, so a wake between
        snapshot and sleep can't be lost (reference semantics:
        mutable-object WaitForWritten/WaitForReadable,
        core_worker/experimental_mutable_object_manager.h:48,153 —
        which block on a shared condvar, same shape)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spin_until = time.monotonic_ns() + _SPIN_NS
        while not cond():
            if self._closed or self._shared_closed():
                raise ChannelClosedError(self.name)
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"{label} on {self.name}")
            if _FUTEX is None or _ATOMICS is None:
                time.sleep(0.0002)
                continue
            if time.monotonic_ns() < spin_until:
                continue
            with self._io_lock:
                if self._closed:
                    raise ChannelClosedError(self.name)
                addr = self._base_addr + watch_offset
                snap = int(_ATOMICS[0](addr)) & 0xFFFFFFFF
            # Bounded sleep; EAGAIN (counter already moved) and
            # spurious wakeups just re-run the loop. The segment can't
            # be unmapped out from under the kernel wait by our own
            # close() (io_lock above re-checked _closed), and a peer
            # unmap at worst faults the wait into an error return.
            _FUTEX[0](addr, snap, _WAIT_CHUNK_NS)

    def _ring_doorbell(self, watch_offset: int) -> None:
        if _FUTEX is None:
            return
        with self._io_lock:
            if self._closed:
                return
            _FUTEX[1](self._base_addr + watch_offset, 2**31 - 1)

    # -- public --------------------------------------------------------
    def put_bytes(self, payload: bytes, timeout: Optional[float] = None):
        record = len(payload) + _LEN
        if record > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel "
                f"capacity {self.capacity}; recompile with a larger "
                "buffer_size_bytes"
            )
        # Ring full: wait for the reader to advance tail (offset 8).
        self._await(
            lambda: self.capacity - (self._head() - self._tail())
            >= record,
            8,
            timeout,
            "put",
        )
        head = self._head()
        self._write_at(head, struct.pack("<Q", len(payload)))
        self._write_at(head + _LEN, payload)
        self._set_head(head + record)
        self._ring_doorbell(0)  # wake a reader sleeping on head

    def get_bytes(self, timeout: Optional[float] = None) -> bytes:
        # Ring empty: wait for the writer to advance head (offset 0).
        self._await(
            lambda: self._head() - self._tail() >= _LEN, 0, timeout, "get"
        )
        tail = self._tail()
        (size,) = struct.unpack("<Q", self._read_at(tail, _LEN))
        payload = self._read_at(tail + _LEN, size)
        self._set_tail(tail + _LEN + size)
        self._ring_doorbell(8)  # wake a writer sleeping on tail
        return payload

    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        self.put_bytes(pickle.dumps(value), timeout=timeout)

    def get(self, timeout: Optional[float] = None) -> Any:
        return pickle.loads(self.get_bytes(timeout=timeout))

    def close(self) -> None:
        try:
            # Shared flag first (while still mapped): a peer blocked in
            # put/get on the other side of the ring sees it and raises
            # instead of spinning forever (`_closed` is process-local).
            self._store(16, 1)
            # Ring both doorbells so a peer sleeping in the kernel
            # notices immediately (it would otherwise wait out one
            # bounded chunk).
            self._ring_doorbell(0)
            self._ring_doorbell(8)
        except Exception:
            pass
        with self._io_lock:
            self._closed = True
            try:
                self._shm.close()
            except BufferError:
                pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __reduce__(self):
        # Deserializing attaches to the same segment (reader side).
        return (_attach, (self.name, self.capacity))


def _attach(name: str, capacity: int) -> "ShmChannel":
    return ShmChannel(capacity, name=name, create=False)
