"""Shared-memory SPSC channels for compiled DAGs.

Reference: python/ray/experimental/channel/shared_memory_channel.py:159
— compiled graphs move data over mutable plasma buffers with
acquire/release semantics (core_worker/experimental_mutable_object_
manager.h:48) instead of per-call RPC. Here a channel is a POSIX
shared-memory ring buffer: single writer, single reader, length-framed
pickled records, monotonic head/tail counters in the segment header.
Same-host only by design — cross-host stage boundaries in a TPU
pipeline ride ICI/DCN collectives inside the jitted program
(parallel/pipeline), not the control-plane channel.
"""

from __future__ import annotations

import ctypes
import pickle
import struct
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

_HEADER = 24  # three u64s: head (written), tail (read), closed flag
_LEN = 8  # per-record length prefix

#: On TSO architectures CPython's sequential bytecode execution plus
#: the hardware ordering make plain counter loads/stores correct for
#: the release/acquire pattern below; elsewhere publication goes
#: through the native atomics. Gating on machine() keeps the hot path
#: at ~0.1us/counter-op (memoryview index) instead of ~1.5us (lock +
#: ctypes FFI round trip) — the difference is 2x on whole-hop latency
#: (MICROBENCH dag_hop_per_s).
import platform as _platform

_TSO = _platform.machine() in ("x86_64", "AMD64", "i686", "i386")


def _load_native(symbol: str):
    """The native library if it loads AND exposes `symbol` (older .so
    builds predate some entry points), else None."""
    try:
        from .._native import load_library

        lib = load_library()
        if lib is not None and hasattr(lib, symbol):
            return lib
    except Exception:
        pass
    return None


#: (load_acquire, store_release) on u64 addresses — real fences,
#: correct on any architecture. None falls back to plain struct
#: access, safe on x86-TSO where CPython's stores aren't reordered.
_lib = _load_native("rts_load_acq_u64")
_ATOMICS = (
    (_lib.rts_load_acq_u64, _lib.rts_store_rel_u64) if _lib else None
)
#: (wait, wake) on the low u32 word of a counter — the kernel-sleep
#: half of the doorbell; spin covers the hot path.
_lib = _load_native("rts_futex_wait_u32")
_FUTEX = (_lib.rts_futex_wait_u32, _lib.rts_futex_wake) if _lib else None
#: Whole-op native ring put/get (store.cc rts_chan_put/get). One FFI
#: call per operation instead of ~6 plus interpreter work: measured
#: 39us -> ~25us per two-process ping-pong hop on the 1-core CI box
#: (vs a 6.9us OS-pipe floor), and the compiled-DAG hop 8.3k -> 23k/s.
_CHAN_NATIVE = _load_native("rts_chan_put")
del _lib
import errno as _errno
#: Hot-spin budget before sleeping in the kernel: covers the common
#: compiled-pipeline turnaround (~tens of us) without a syscall. On a
#: single-CPU machine spinning is counterproductive — the waiter burns
#: the exact quantum its peer needs to produce the data — so go
#: straight to the futex there.
import os as _os

_SPIN_NS = 100_000 if (_os.cpu_count() or 1) > 1 else 0
#: Bounded kernel waits so a peer's close() (shared flag, no doorbell
#: reachable after unmap) is noticed promptly even with no traffic.
_WAIT_CHUNK_NS = 20_000_000

STOP = b"__RT_DAG_STOP__"


class ChannelClosedError(Exception):
    pass


class ChannelTimeoutError(Exception):
    pass


class ShmChannel:
    """Single-producer single-consumer shared-memory ring buffer."""

    def __init__(
        self,
        capacity: int = 4 * 1024 * 1024,
        *,
        name: Optional[str] = None,
        create: bool = True,
    ):
        # Round up to a u64 multiple: the counter view below casts the
        # whole segment to "Q", which requires 8-divisible length (and
        # the ring's length-prefixed records don't care).
        capacity = (capacity + 7) & ~7
        self.capacity = capacity
        if create:
            self._shm = shared_memory.SharedMemory(
                create=True, size=_HEADER + capacity
            )
            self._shm.buf[:_HEADER] = b"\x00" * _HEADER
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # The creator owns the segment lifetime; stop the attaching
            # process's resource tracker from unlinking it at exit.
            try:
                resource_tracker.unregister(
                    self._shm._name, "shared_memory"  # noqa: SLF001
                )
            except Exception:
                pass
        self.name = self._shm.name
        self._closed = False
        # Base address of the header for the native atomic accessors.
        self._base_addr = ctypes.addressof(
            ctypes.c_char.from_buffer(self._shm.buf)
        )
        # u64 view over the whole segment; indices 0/1/2 are
        # head/tail/closed. The hot paths below index this directly —
        # a memoryview load is ~15x cheaper than a lock + FFI call.
        self._u64 = self._shm.buf.cast("Q")
        # Guards counter access against close() unmapping the segment:
        # a native atomic load on an unmapped address is a segfault,
        # not an exception.
        self._io_lock = threading.Lock()
        # Whole-op native path state: reusable receive buffer and the
        # count of threads currently inside a native call (close()
        # must not unmap the segment under them). The per-direction
        # locks serialize concurrent callers of the same operation —
        # the ring is SPSC, and the native path must keep the Python
        # path's per-op atomicity (two concurrent getters would race
        # the shared scratch buffer; two putters the head counter).
        self._scratch = None
        self._inflight = 0
        self._tx_lock = threading.Lock()
        self._rx_lock = threading.Lock()

    # -- counters ------------------------------------------------------
    # Counter reads/writes live inline in put_bytes/get_bytes/_await
    # (single lock round, _u64 view on TSO, FFI release/acquire
    # elsewhere). _store survives only for close()'s shared flag.
    def _store(self, offset: int, v: int) -> None:
        with self._io_lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            if _ATOMICS is not None:
                _ATOMICS[1](self._base_addr + offset, v)
                return
            struct.pack_into("<Q", self._shm.buf, offset, v)

    # -- ring IO -------------------------------------------------------
    def _write_at(self, pos: int, payload: bytes) -> None:
        offset = pos % self.capacity
        first = min(len(payload), self.capacity - offset)
        base = _HEADER + offset
        self._shm.buf[base : base + first] = payload[:first]
        if first < len(payload):
            rest = len(payload) - first
            self._shm.buf[_HEADER : _HEADER + rest] = payload[first:]

    def _read_at(self, pos: int, size: int) -> bytes:
        offset = pos % self.capacity
        first = min(size, self.capacity - offset)
        base = _HEADER + offset
        out = bytes(self._shm.buf[base : base + first])
        if first < size:
            out += bytes(self._shm.buf[_HEADER : _HEADER + size - first])
        return out

    # -- blocking ------------------------------------------------------
    def _await(self, need, watch_offset: int, timeout, label: str):
        """Block until `need(head, tail)` holds. Adaptive: hot-spin
        for a short budget (covers the in-flight-producer case with
        zero syscalls), then sleep in the kernel on the counter at
        `watch_offset` via futex until the peer's doorbell — or
        sleep-poll when the native library is absent. The futex
        compares the counter's low u32 in-kernel, so a wake between
        snapshot and sleep can't be lost (reference semantics:
        mutable-object WaitForWritten/WaitForReadable,
        core_worker/experimental_mutable_object_manager.h:48,153 —
        which block on a shared condvar, same shape). One lock round
        per cycle: on a one-core box every hop sleeps here, so this
        path is as hot as put/get themselves."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spin_until = time.monotonic_ns() + _SPIN_NS
        use_futex = _FUTEX is not None and _ATOMICS is not None
        while True:
            with self._io_lock:
                if self._closed:
                    raise ChannelClosedError(self.name)
                u = self._u64
                if _ATOMICS is not None and not _TSO:
                    head = int(_ATOMICS[0](self._base_addr))
                    tail = int(_ATOMICS[0](self._base_addr + 8))
                else:
                    head, tail = u[0], u[1]
                if need(head, tail):
                    return
                if u[2]:
                    raise ChannelClosedError(self.name)
                snap = (head if watch_offset == 0 else tail) & 0xFFFFFFFF
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"{label} on {self.name}")
            if not use_futex:
                time.sleep(0.0002)
                continue
            if time.monotonic_ns() < spin_until:
                continue
            # Bounded sleep; EAGAIN (counter already moved) and
            # spurious wakeups just re-run the loop. The segment can't
            # be unmapped out from under the kernel wait by our own
            # close() (io_lock above re-checked _closed), and a peer
            # unmap at worst faults the wait into an error return.
            _FUTEX[0](self._base_addr + watch_offset, snap, _WAIT_CHUNK_NS)

    def _ring_doorbell(self, watch_offset: int) -> None:
        if _FUTEX is None:
            return
        with self._io_lock:
            if self._closed:
                return
            _FUTEX[1](self._base_addr + watch_offset, 2**31 - 1)

    # -- public --------------------------------------------------------
    # The hot paths take _io_lock ONCE per operation and touch the
    # counters through the u64 view: the previous structure (a locked
    # FFI round trip per counter access, five per put/get) measured
    # ~23us per put+get pair against a 4.7us OS pipe ping-pong floor —
    # the channel layer, not scheduling, dominated compiled-DAG hop
    # latency. Publication ordering: payload bytes are stored before
    # the head/tail bump; TSO hardware (x86) preserves that order for
    # plain stores, other architectures publish through the native
    # store-release.
    # -- whole-op native path ------------------------------------------
    def _native_enter(self):
        with self._io_lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            self._inflight += 1

    def _native_exit(self):
        with self._io_lock:
            self._inflight -= 1

    def _native_put(self, payload: bytes, timeout: Optional[float]):
        t_ns = -1 if timeout is None else max(0, int(timeout * 1e9))
        with self._tx_lock:
            self._native_enter()
            try:
                rc = _CHAN_NATIVE.rts_chan_put(
                    self._base_addr, self.capacity, payload,
                    len(payload), t_ns,
                )
            finally:
                self._native_exit()
        if rc == 0:
            return
        if rc == -_errno.EPIPE:
            raise ChannelClosedError(self.name)
        if rc == -_errno.ETIMEDOUT:
            raise ChannelTimeoutError(f"put on {self.name}")
        if rc == -_errno.EMSGSIZE:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel "
                f"capacity {self.capacity}; recompile with a larger "
                "buffer_size_bytes"
            )
        raise RuntimeError(f"native channel put failed: rc={rc}")

    def _native_get(self, timeout: Optional[float]) -> bytes:
        t_ns = -1 if timeout is None else max(0, int(timeout * 1e9))
        with self._rx_lock:
            if self._scratch is None:
                self._scratch = ctypes.create_string_buffer(
                    self.capacity
                )
            self._native_enter()
            try:
                n = _CHAN_NATIVE.rts_chan_get(
                    self._base_addr, self.capacity, self._scratch,
                    self.capacity, t_ns,
                )
            finally:
                self._native_exit()
            if n >= 0:
                return self._scratch[:n]
        if n == -_errno.EPIPE:
            raise ChannelClosedError(self.name)
        if n == -_errno.ETIMEDOUT:
            raise ChannelTimeoutError(f"get on {self.name}")
        raise RuntimeError(f"native channel get failed: rc={n}")

    def put_bytes(self, payload: bytes, timeout: Optional[float] = None):
        if _CHAN_NATIVE is not None:
            return self._native_put(payload, timeout)
        record = len(payload) + _LEN
        if record > self.capacity:
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel "
                f"capacity {self.capacity}; recompile with a larger "
                "buffer_size_bytes"
            )
        # One deadline for the WHOLE call: _await may be re-entered
        # (another thread can consume freed space first), and a
        # restarted timeout would block past the caller's bound.
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._io_lock:
                if self._closed:
                    raise ChannelClosedError(self.name)
                u = self._u64
                if u[2]:
                    raise ChannelClosedError(self.name)
                head = u[0]
                if self.capacity - (head - u[1]) >= record:
                    self._write_at(head, struct.pack("<Q", len(payload)))
                    self._write_at(head + _LEN, payload)
                    if _ATOMICS is not None and not _TSO:
                        _ATOMICS[1](self._base_addr, head + record)
                    else:
                        u[0] = head + record
                    if _FUTEX is not None:  # wake a reader on head
                        _FUTEX[1](self._base_addr, 2**31 - 1)
                    return
            # Ring full: wait for the reader to advance tail (off 8).
            self._await(
                lambda head, tail: self.capacity - (head - tail)
                >= record,
                8,
                None if deadline is None
                else deadline - time.monotonic(),
                "put",
            )

    def get_bytes(self, timeout: Optional[float] = None) -> bytes:
        if _CHAN_NATIVE is not None:
            return self._native_get(timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._io_lock:
                if self._closed:
                    raise ChannelClosedError(self.name)
                u = self._u64
                tail = u[1]
                head = (
                    int(_ATOMICS[0](self._base_addr))
                    if _ATOMICS is not None and not _TSO
                    else u[0]
                )
                if head - tail >= _LEN:
                    (size,) = struct.unpack(
                        "<Q", self._read_at(tail, _LEN)
                    )
                    payload = self._read_at(tail + _LEN, size)
                    if _ATOMICS is not None and not _TSO:
                        _ATOMICS[1](
                            self._base_addr + 8, tail + _LEN + size
                        )
                    else:
                        u[1] = tail + _LEN + size
                    if _FUTEX is not None:  # wake a writer on tail
                        _FUTEX[1](self._base_addr + 8, 2**31 - 1)
                    return payload
                if u[2]:
                    raise ChannelClosedError(self.name)
            # Ring empty: wait for the writer to advance head (off 0).
            self._await(
                lambda head, tail: head - tail >= _LEN,
                0,
                None if deadline is None
                else deadline - time.monotonic(),
                "get",
            )

    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        self.put_bytes(pickle.dumps(value), timeout=timeout)

    def get(self, timeout: Optional[float] = None) -> Any:
        return pickle.loads(self.get_bytes(timeout=timeout))

    def close(self) -> None:
        try:
            # Shared flag first (while still mapped): a peer blocked in
            # put/get on the other side of the ring sees it and raises
            # instead of spinning forever (`_closed` is process-local).
            self._store(16, 1)
            # Ring both doorbells so a peer sleeping in the kernel
            # notices immediately (it would otherwise wait out one
            # bounded chunk).
            self._ring_doorbell(0)
            self._ring_doorbell(8)
        except Exception:
            pass
        # A thread blocked inside a whole-op native call holds a raw
        # pointer into the mapping; it has just been woken (closed
        # flag + doorbells) and will exit with EPIPE — wait it out
        # before unmapping (unmapping under it would segfault, not
        # raise). Bounded: native waits re-check in <=200ms chunks.
        deadline = time.monotonic() + 2.0
        while True:
            with self._io_lock:
                if self._inflight == 0 or time.monotonic() > deadline:
                    self._closed = True
                    busy = self._inflight > 0
                    if not busy:
                        try:
                            self._u64.release()
                        except Exception:
                            pass
                        try:
                            self._shm.close()
                        except BufferError:
                            pass
                    # busy after the grace: leave the mapping in place
                    # (freed at GC) rather than segfault a straggler.
                    return
            try:
                self._ring_doorbell(0)
                self._ring_doorbell(8)
            except Exception:
                pass
            time.sleep(0.001)

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __del__(self):
        # Release the cast view before SharedMemory.__del__ runs its
        # own close(), which otherwise reports un-catchable
        # "exported pointers exist" BufferErrors at GC time.
        try:
            self._u64.release()
        except Exception:
            pass

    def __reduce__(self):
        # Deserializing attaches to the same segment (reader side).
        return (_attach, (self.name, self.capacity))


def _attach(name: str, capacity: int) -> "ShmChannel":
    return ShmChannel(capacity, name=name, create=False)
