"""Lazy DAG node types.

Reference: python/ray/dag/dag_node.py + class_node.py /
function_node.py — `.bind()` builds a lazy graph of task / actor-method
invocations; `InputNode` marks the runtime argument;
`MultiOutputNode` fans multiple leaves out to the caller. `execute()`
walks the graph submitting ordinary remote calls; `experimental_compile`
lowers it to persistent per-actor loops over channels (compiled.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """One vertex: an operation plus bound (possibly nested) args."""

    def __init__(self, bound_args: Tuple[Any, ...], bound_kwargs: dict):
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # -- traversal -----------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for arg in list(self._bound_args) + list(
            self._bound_kwargs.values()
        ):
            if isinstance(arg, DAGNode):
                out.append(arg)
        return out

    def topological_order(self) -> List["DAGNode"]:
        """Children-before-parents order over the reachable graph."""
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for child in node._children():
                visit(child)
            order.append(node)

        visit(self)
        return order

    # -- interpreted execution ----------------------------------------
    def execute(self, *input_values):
        """Walk the DAG submitting ordinary remote calls; returns the
        root's ObjectRef (or a list for MultiOutputNode)."""
        cache: Dict[int, Any] = {}
        order = self.topological_order()
        for node in order:
            cache[id(node)] = node._apply(
                [
                    cache[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._bound_args
                ],
                {
                    k: cache[id(v)] if isinstance(v, DAGNode) else v
                    for k, v in node._bound_kwargs.items()
                },
                input_values,
            )
        return cache[id(self)]

    def _apply(self, args, kwargs, input_values):
        raise NotImplementedError

    def experimental_compile(
        self, buffer_size_bytes: int = 4 * 2**20
    ):
        """Lower this actor DAG to persistent per-actor loops over
        shared-memory channels (reference:
        dag_node.experimental_compile -> CompiledDAG)."""
        return experimental_compile(self, buffer_size_bytes)


class InputNode(DAGNode):
    """Placeholder for the value passed to `execute()` /
    `compiled.execute()` (reference: python/ray/dag/input_node.py)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _apply(self, args, kwargs, input_values):
        if len(input_values) != 1:
            raise ValueError(
                f"DAG has one InputNode; execute() takes exactly one "
                f"argument (got {len(input_values)})"
            )
        return input_values[0]

    def __getitem__(self, key) -> "InputAttributeNode":
        """`inp[0]` / `inp["x"]` — bind a projection of the runtime
        input (reference: python/ray/dag/input_node.py
        InputAttributeNode), so one execute() value fans different
        fields out to different nodes."""
        return InputAttributeNode(self, key)

    def __iter__(self):
        # __getitem__ would otherwise make this "iterable" via the
        # legacy protocol — an infinite stream of projection nodes
        # (`for x in inp:` / `a, b = inp` would hang or mislead).
        raise TypeError(
            "InputNode is not iterable; bind explicit projections "
            "(inp[0], inp[1], ...) instead"
        )


class InputAttributeNode(DAGNode):
    """A key/index projection of the InputNode's runtime value."""

    def __init__(self, input_node: InputNode, key):
        super().__init__((input_node,), {})
        self.key = key

    @property
    def input_node(self) -> InputNode:
        return self._bound_args[0]

    def _apply(self, args, kwargs, input_values):
        # args[0] is the InputNode's applied value (the raw input).
        return args[0][self.key]


class FunctionNode(DAGNode):
    """`remote_fn.bind(...)` — a task invocation."""

    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self._rf = remote_function

    def _apply(self, args, kwargs, input_values):
        return self._rf.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    """`actor.method.bind(...)` — an actor-method invocation."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = actor_handle
        self._method = method_name

    @property
    def actor_handle(self):
        return self._handle

    @property
    def method_name(self) -> str:
        return self._method

    def _apply(self, args, kwargs, input_values):
        method = getattr(self._handle, self._method)
        return method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Fans N leaves out to the caller (reference:
    python/ray/dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _apply(self, args, kwargs, input_values):
        return list(args)


def experimental_compile(dag: DAGNode, buffer_size_bytes: int = 4 * 2**20):
    from .compiled import CompiledDAG

    return CompiledDAG(dag, buffer_size_bytes=buffer_size_bytes)
