"""Cross-node SPSC channels for compiled DAGs.

Reference: src/ray/protobuf/node_manager.proto:467-469 + core_worker/
experimental_mutable_object_manager.h — compiled-graph mutable objects
are *pushed* to the reader's node when writer and reader live on
different nodes, so a pipeline stage boundary can cross hosts without
falling back to per-call task RPC. Here the cross-node edge is a
direct TCP stream between the two workers with the same length-framed
record protocol as the same-host shm ring (`channels.py`):

- the READER binds an ephemeral port on its node and publishes
  ``host:port`` under the channel id in the GCS KV (namespace
  ``dagchan``) — the same rendezvous table function export uses;
- the WRITER polls the KV for the address and connects.

Roles are assigned lazily by the first operation (first ``get`` makes
this end the reader, first ``put`` the writer), so a channel descriptor
pickles to either side of the edge unchanged. TCP's bounded socket
buffers provide the backpressure the shm ring gets from its capacity:
a slow reader eventually blocks the writer's ``send``.

Timeout semantics match ShmChannel where physics allows: a timed-out
``get`` preserves partially-received bytes and resumes the SAME record
on retry (``CompiledDAGRef.get`` documents retry-after-timeout as
safe); a timed-out ``put`` preserves unsent bytes and flushes them
before the next record — so a record is never torn mid-frame, though
unlike shm a put that timed out mid-send will still complete delivery
on the next operation (TCP cannot un-send).

Record identity: every record is framed with a per-channel
monotonically increasing sequence number ([u64 len][u64 seq] header).
A put that times out raises ``ChannelTimeoutError`` carrying the
record's ``seq``; retrying the SAME record means calling
``put_bytes(payload, seq=err.seq)`` — the channel finishes delivering
that record exactly once. A put WITHOUT a retry token is always a new
record, even if its bytes equal a pending one: dedup is by sequence
number, never payload equality (two execute() calls with equal inputs
are two records — comparing bytes silently dropped one and desynced
the driver's result sequencing). The reader verifies the sequence is
gapless and drops any duplicate seq, so the no-dup/no-loss guarantee
is end-to-end.

Dense tensor traffic between TPU pipeline stages still rides ICI
collectives inside the jitted program (parallel/pipeline.py); these
channels carry the control-plane records (activations for CPU stages,
small tensors, errors, stop tokens).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
import uuid
from typing import Any, Optional

from .channels import ChannelClosedError, ChannelTimeoutError

#: Record header: u64 payload length + u64 sequence number. (The
#: same-host shm ring keeps its bare length prefix — its records never
#: retry across a reconnectable transport, so it needs no identity.)
_HDR = 16
_KV_NS = "dagchan"
_POLL_S = 0.02


def _kv_call(method: str, **kw) -> dict:
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise ChannelClosedError("no worker/driver runtime for KV rendezvous")
    return worker.call(method, **kw)


def _advertise_ip() -> str:
    """The IP other nodes can reach this process at. Single-box
    clusters (tests, FakeMultiNode) resolve to loopback."""
    import os

    ip = os.environ.get("RT_NODE_IP")
    if ip:
        return ip
    from .._private.rpc import _detect_host_ip

    return _detect_host_ip()


class TcpChannel:
    """SPSC stream channel across nodes; same put/get surface as
    ShmChannel so compiled-DAG loops are transport-agnostic."""

    def __init__(self, capacity: int = 4 * 1024 * 1024, *,
                 chan_id: Optional[str] = None):
        self.capacity = capacity
        self.chan_id = chan_id or uuid.uuid4().hex
        self.name = f"tcpchan-{self.chan_id[:12]}"
        self._sock: Optional[socket.socket] = None
        self._listener: Optional[socket.socket] = None
        self._role: Optional[str] = None
        self._closed = False
        #: Guards the small mutable state only — never held across a
        #: blocking accept/recv/send/KV poll, so close() can always
        #: acquire it and interrupt a blocked peer by closing the
        #: socket under it.
        self._lock = threading.Lock()
        #: Serializes first-use setup; close() does NOT take it.
        self._setup_lock = threading.Lock()
        # Resumable-IO state: bytes of the current inbound record
        # (header included) and the unsent tail of the current
        # outbound record — a timeout leaves these intact so a retry
        # continues the same record instead of desyncing the stream.
        self._rx = bytearray()
        self._tx = b""
        # Sequence framing (writer side): seq of the record currently
        # pending in _tx, the next seq to allocate, and the highest
        # seq fully handed to the kernel — a retry token is matched
        # against these, so dedup is by record identity, never by
        # payload bytes.
        self._tx_seq: Optional[int] = None
        self._next_tx_seq = 0
        self._last_sent_seq = -1
        # Reader side: next sequence number the stream owes us.
        self._rx_next_seq = 0

    # -- rendezvous ----------------------------------------------------
    def bind_reader(self) -> None:
        """Bind + publish this end as the reader WITHOUT accepting.
        The compiled-DAG driver calls this at compile time for its
        output channels so a stage's first put() can always resolve an
        address and complete into the TCP backlog/kernel buffers —
        even if the driver never reads (teardown-without-get must not
        wedge the stage's exec loop in rendezvous)."""
        with self._lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            if self._role is None:
                self._role = "reader"
            elif self._role != "reader":
                raise RuntimeError(f"{self.name} already a {self._role}")
        with self._setup_lock:
            self._bind_and_publish()

    def _bind_and_publish(self) -> Optional[socket.socket]:
        """Create + publish the listener exactly once; returns it (or
        None if the channel closed underneath)."""
        with self._lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            listener = self._listener
            if listener is not None:
                return listener
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(("0.0.0.0", 0))
            listener.listen(1)
            self._listener = listener
            port = listener.getsockname()[1]
        addr = f"{_advertise_ip()}:{port}"
        _kv_call("kv_put", ns=_KV_NS, key=self.chan_id,
                 value=addr.encode(), overwrite=True)
        return listener

    def _ensure(self, role: str, timeout: Optional[float]) -> socket.socket:
        with self._lock:
            if self._closed:
                raise ChannelClosedError(self.name)
            if self._role is None:
                self._role = role
            elif self._role != role:
                raise RuntimeError(
                    f"{self.name} already bound as {self._role}; SPSC "
                    f"channels serve one direction per endpoint"
                )
            if self._sock is not None:
                return self._sock
        with self._setup_lock:
            with self._lock:
                if self._closed:
                    raise ChannelClosedError(self.name)
                if self._sock is not None:
                    return self._sock
            if role == "reader":
                return self._setup_reader(timeout)
            return self._setup_writer(timeout)

    def _setup_reader(self, timeout: Optional[float]) -> socket.socket:
        # Bind + publish exactly once; an accept timeout keeps the
        # listener (and its published address) so a retried get()
        # accepts on the SAME port — rebinding would strand a writer
        # that already resolved the old address.
        listener = self._bind_and_publish()
        listener.settimeout(timeout)
        try:
            conn, _ = listener.accept()  # rt: noqa[RT203] — _setup_lock serializes connection setup; the accept IS the setup step
        except socket.timeout:
            raise ChannelTimeoutError(
                f"accept on {self.name} (writer not connected yet)"
            ) from None
        except OSError:
            # close() shut the listener under us.
            raise ChannelClosedError(self.name) from None
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Bound kernel-buffered bytes to the channel capacity so a
        # stalled reader applies backpressure at roughly the same
        # high-water mark as the shm ring.
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                        min(self.capacity, 4 * 1024 * 1024))
        with self._lock:
            if self._closed:
                conn.close()
                raise ChannelClosedError(self.name)
            self._sock = conn
            listener.close()
            self._listener = None
        return conn

    def _setup_writer(self, timeout: Optional[float]) -> socket.socket:
        # timeout=None blocks indefinitely, matching a ShmChannel put
        # against an absent reader (the reader binds on its first
        # get(), which for DAG output edges is the driver's first
        # ref.get() — arbitrarily later than the stage's first put).
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._closed:
                raise ChannelClosedError(self.name)
            reply = _kv_call("kv_get", ns=_KV_NS, key=self.chan_id)
            value = reply.get("value")
            if value:
                addr = value.decode()
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"rendezvous on {self.name} (no reader address)"
                )
            time.sleep(_POLL_S)  # rt: noqa[RT203] — setup-time retry backoff under the setup lock: nothing else may connect meanwhile
        host, port = addr.rsplit(":", 1)
        while True:
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=5.0
                )
                break
            except OSError:
                if self._closed:
                    raise ChannelClosedError(self.name) from None
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(
                        f"connect to {addr} for {self.name}"
                    ) from None
                time.sleep(_POLL_S)  # rt: noqa[RT203] — setup-time retry backoff under the setup lock: nothing else may connect meanwhile
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                        min(self.capacity, 4 * 1024 * 1024))
        with self._lock:
            if self._closed:
                sock.close()
                raise ChannelClosedError(self.name)
            self._sock = sock
        return sock

    # -- IO ------------------------------------------------------------
    def put_bytes(self, payload: bytes,
                  timeout: Optional[float] = None, *,
                  seq: Optional[int] = None) -> int:
        """Send one record; returns its sequence number.

        `seq` is a RETRY TOKEN only: pass the `.seq` carried by a
        previous ChannelTimeoutError to finish delivering that exact
        record (already-delivered tokens are a no-op). Without a
        token every call is a new record — identical bytes do NOT
        make a retry (see module docstring: dedup is by sequence
        number, never payload equality).
        """
        if len(payload) + _HDR > self.capacity:
            # Same contract as the shm ring: placement must not decide
            # whether an oversized record is accepted.
            raise ValueError(
                f"message of {len(payload)} bytes exceeds channel "
                f"capacity {self.capacity}; recompile with a larger "
                "buffer_size_bytes"
            )
        if seq is not None and seq != self._tx_seq:
            if seq <= self._last_sent_seq:
                return seq  # retry of a fully delivered record: no-op
            raise ValueError(
                f"unknown retry token seq={seq} on {self.name} (pending="
                f"{self._tx_seq}, last sent={self._last_sent_seq})"
            )
        sock = self._ensure("writer", timeout)
        sock.settimeout(timeout)
        try:
            if self._tx:
                # Finish the partially-sent previous record first —
                # the stream must never interleave frames. If the
                # caller holds that record's retry token, flushing IS
                # the send; otherwise this is a new record behind it.
                pending_seq = self._tx_seq
                try:
                    self._flush_locked_state(sock)
                except socket.timeout:
                    err = ChannelTimeoutError(f"put on {self.name}")
                    # The token belongs to whoever queued the pending
                    # record. A caller submitting a NEW record gets no
                    # token — its record was never accepted, so its
                    # retry is a plain put_bytes() again.
                    err.seq = pending_seq if seq == pending_seq else None
                    raise err from None
                if seq is not None and seq == pending_seq:
                    return seq
            cur = self._next_tx_seq
            self._next_tx_seq += 1
            self._tx = memoryview(
                struct.pack("<QQ", len(payload), cur) + payload
            )
            self._tx_seq = cur
            try:
                self._flush_locked_state(sock)
            except socket.timeout:
                err = ChannelTimeoutError(f"put on {self.name}")
                # The retry token: put_bytes(payload, seq=err.seq)
                # resumes THIS record instead of queueing a duplicate.
                err.seq = cur
                raise err from None
            return cur
        except OSError:
            raise ChannelClosedError(self.name) from None

    def _flush_locked_state(self, sock: socket.socket) -> None:
        while self._tx:
            n = sock.send(self._tx)
            self._tx = self._tx[n:]
        if self._tx_seq is not None:
            self._last_sent_seq = max(self._last_sent_seq, self._tx_seq)
            self._tx_seq = None

    def get_bytes(self, timeout: Optional[float] = None) -> bytes:
        sock = self._ensure("reader", timeout)
        sock.settimeout(timeout)
        try:
            while True:
                while len(self._rx) < _HDR:
                    self._recv_into(sock, 65536)
                size, seq = struct.unpack_from("<QQ", self._rx)
                total = _HDR + size
                while len(self._rx) < total:
                    self._recv_into(
                        sock, min(total - len(self._rx), 1 << 20)
                    )
                payload = bytes(self._rx[_HDR:total])
                del self._rx[:total]
                if seq == self._rx_next_seq:
                    self._rx_next_seq = seq + 1
                    return payload
                if seq < self._rx_next_seq:
                    # Duplicate of a delivered record (writer-side
                    # dedup failed us): drop it — end-to-end exactly-
                    # once beats trusting the peer.
                    continue
                # A gap means records were lost or the peer desynced;
                # no read can ever succeed again — fail loudly rather
                # than hand the caller out-of-order results.
                raise RuntimeError(
                    f"{self.name}: sequence gap (expected "
                    f"{self._rx_next_seq}, got {seq})"
                )
        except socket.timeout:
            # _rx keeps the partial record; the retried get() resumes.
            raise ChannelTimeoutError(f"get on {self.name}") from None
        except OSError:
            raise ChannelClosedError(self.name) from None

    def _recv_into(self, sock: socket.socket, limit: int) -> None:
        chunk = sock.recv(limit)
        if not chunk:
            raise ChannelClosedError(self.name)
        self._rx += chunk

    def put(self, value: Any, timeout: Optional[float] = None, *,
            seq: Optional[int] = None) -> int:
        """Pickle + send; returns the record's seq. `seq` is the retry
        token from a previous put's ChannelTimeoutError (`err.seq`) —
        it makes the retry finish delivering THAT record instead of
        queueing a duplicate (see put_bytes)."""
        return self.put_bytes(pickle.dumps(value), timeout=timeout,
                              seq=seq)

    def get(self, timeout: Optional[float] = None) -> Any:
        return pickle.loads(self.get_bytes(timeout=timeout))

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sock, listener = self._sock, self._listener
            self._sock = self._listener = None
        # Outside the state lock: a peer blocked in accept/recv/send
        # observes the shutdown as an OSError -> ChannelClosedError;
        # a writer polling the KV sees _closed within one poll tick.
        for s in (sock, listener):
            if s is None:
                continue
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def unlink(self) -> None:
        # Drop the rendezvous key; KV is session-scoped so a leak is
        # bounded, but compiled DAGs are created/torn down repeatedly.
        try:
            _kv_call("kv_del", ns=_KV_NS, key=self.chan_id)
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __reduce__(self):
        # The far side materializes a fresh endpoint of the same
        # channel; roles bind on first use.
        return (_attach, (self.chan_id, self.capacity))


def _attach(chan_id: str, capacity: int) -> "TcpChannel":
    return TcpChannel(capacity, chan_id=chan_id)
