"""Compiled DAG execution.

Reference: python/ray/dag/compiled_dag_node.py:691 — compiling an
actor DAG replaces per-call task RPC with persistent per-actor
execution loops connected by channels: each actor blocks on its input
channel(s), runs its bound method, and pushes the result downstream.
One `execute()` then costs channel writes instead of scheduler
round-trips, which is what pipelines (micro-batched inference/training
stages) need.

Protocol records on every channel: ("v", value) | ("e", exception) |
("s", None) for stop. Errors and stop tokens propagate downstream so
one teardown() at the driver drains the whole pipeline.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..actor import ActorMethod
from .channels import ChannelTimeoutError, ShmChannel
from .edges import Edge
from .tcp_channel import TcpChannel
from .dag_node import (
    ClassMethodNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

#: Sentinel key for "the whole input value" (no projection).
_WHOLE = object()

DAG_LOOP_METHOD = "__rt_dag_loop__"


def wait_actor_placements(
    actor_handles, timeout: float = 30.0
) -> Dict[bytes, Optional[str]]:
    """actor_id bytes -> node_id hex for every handle, polling the
    control plane until each actor has been placed (a just-created
    actor may still be leasing a worker). Shared by compiled-DAG
    channel wiring and the MPMD pipeline's edge placement — both need
    the same-node-or-not decision per edge."""
    from .._private.worker import global_worker

    worker = global_worker()
    want = {h.actor_id.binary() for h in actor_handles}
    deadline = time.monotonic() + timeout
    placement: Dict[bytes, Optional[str]] = {}
    while True:
        rows = worker.call("list_actors")["actors"]
        placement = {
            bytes.fromhex(row["actor_id"]): row["node_id"]
            for row in rows
            if bytes.fromhex(row["actor_id"]) in want
        }
        if len(placement) == len(want) and all(
            v is not None for v in placement.values()
        ):
            return placement
        if time.monotonic() > deadline:
            raise RuntimeError(
                "actors not placed within "
                f"{timeout}s (have {len(placement)}/{len(want)})"
            )
        time.sleep(0.05)


def dag_exec_loop(
    instance: Any,
    method_name: str,
    arg_descs: List[Tuple[str, Any]],
    out_channels: List[ShmChannel],
):
    """Runs inside the actor (worker._execute special-cases the
    method name): block on inputs, apply, push downstream."""
    try:
        while True:
            args = []
            stop = False
            error = None
            for kind, value in arg_descs:
                if kind == "const":
                    args.append(value)
                    continue
                tag, payload = value.get()
                if tag == "s":
                    stop = True
                elif tag == "e":
                    error = payload
                else:
                    args.append(payload)
            if stop:
                for chan in out_channels:
                    try:
                        chan.put(("s", None), timeout=5)
                    except Exception:
                        pass
                return "stopped"
            if error is not None:
                for chan in out_channels:
                    chan.put(("e", error))
                continue
            try:
                result = getattr(instance, method_name)(*args)
            except BaseException as e:  # noqa: BLE001 — forwarded
                for chan in out_channels:
                    chan.put(("e", e))
                continue
            for chan in out_channels:
                chan.put(("v", result))
    finally:
        for kind, value in arg_descs:
            if kind == "chan":
                value.close()
        for chan in out_channels:
            chan.close()


class CompiledDAGRef:
    """Future for one execute() (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value: Any = None
        self._done = False

    def get(self, timeout: Optional[float] = 30.0):
        if not self._done:
            self._value = self._dag._read_result(self._seq, timeout)
            self._done = True
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size_bytes: int = 4 * 2**20):
        self._root = root
        self._buffer = buffer_size_bytes
        self._lock = threading.Lock()
        self._read_mutex = threading.Lock()
        self._submit_mutex = threading.Lock()
        self._next_seq = 0
        self._next_read_seq = 0
        self._results: Dict[int, Any] = {}
        self._torn_down = False
        #: DAG seqs whose execute() raised (no CompiledDAGRef exists
        #: for them): their eventual outputs are read-and-discarded in
        #: _read_result instead of cached forever.
        self._orphan_seqs: set = set()
        #: Tail of a timed-out execute(): [(chan, record, retry_token)]
        #: for input channels that have NOT yet received that
        #: submission's record. The next execute() (or teardown)
        #: finishes these deliveries FIRST — with the channel's retry
        #: token where one exists — so the torn submission lands
        #: exactly once on every channel and the per-channel record
        #: streams stay aligned with the DAG's seq accounting.
        self._pending_inputs: List[tuple] = []
        #: [(channel, projection key | _WHOLE)] in bind order.
        self._input_channels: List[tuple] = []
        self._output_channels: List[ShmChannel] = []
        self._all_channels: List[ShmChannel] = []
        self._loop_refs = []
        self._compile()

    # -- compilation ---------------------------------------------------
    def _compile(self) -> None:
        order = self._root.topological_order()
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError(
                "compiled DAGs need exactly one InputNode "
                f"(found {len(inputs)})"
            )
        outputs: List[DAGNode]
        if isinstance(self._root, MultiOutputNode):
            outputs = list(self._root._bound_args)
        else:
            outputs = [self._root]
        actor_nodes: List[ClassMethodNode] = []
        seen_actors = set()
        for node in order:
            if isinstance(
                node, (InputNode, InputAttributeNode, MultiOutputNode)
            ):
                continue
            if not isinstance(node, ClassMethodNode):
                raise TypeError(
                    "compiled DAGs support actor-method nodes only; "
                    f"got {type(node).__name__} (use execute() for "
                    "interpreted task DAGs)"
                )
            key = node.actor_handle.actor_id.binary()
            if key in seen_actors:
                raise ValueError(
                    "an actor may appear in at most one compiled-DAG "
                    "node (its execution loop owns the actor)"
                )
            seen_actors.add(key)
            actor_nodes.append(node)
        for out in outputs:
            if not isinstance(out, ClassMethodNode):
                raise TypeError("DAG outputs must be actor-method nodes")

        # One SPSC channel per (producer -> consumer) edge. Same-node
        # edges ride the shm ring; cross-node edges ride a TCP stream
        # (reference: node_manager.proto:467-469 — mutable objects are
        # pushed to the reader's node when the edge crosses nodes).
        placement = self._actor_placements(actor_nodes)
        driver_node = self._driver_node_id()
        in_descs: Dict[int, List[Tuple[str, Any]]] = {}
        out_chans: Dict[int, List[ShmChannel]] = {
            id(n): [] for n in actor_nodes
        }
        def label(n: ClassMethodNode) -> str:
            return (
                f"{n.method_name}@"
                f"{n.actor_handle.actor_id.hex()[:6]}"
            )

        for node in actor_nodes:
            descs: List[Tuple[str, Any]] = []
            node_placement = placement[node.actor_handle.actor_id.binary()]
            for arg in node._bound_args:
                if isinstance(arg, (InputNode, InputAttributeNode)):
                    chan = self._new_channel(driver_node, node_placement)
                    key = (
                        arg.key
                        if isinstance(arg, InputAttributeNode)
                        else _WHOLE
                    )
                    # Edges wrap the raw channel with per-edge
                    # counters (hops/bytes/wait histograms on the
                    # metrics pipe; doctor folds them) — the channel
                    # itself stays in _all_channels for teardown.
                    # Driver IO edges are counters-only (timed=False):
                    # their blocked time is the caller's own
                    # execute()/get() latency, and the ~2 us timed
                    # path would tax the ~25 us hop (MICROBENCH
                    # dag_hop_per_s). Actor->actor edges keep full
                    # wait timing — that's where a straggler stage
                    # shows.
                    edge = Edge(
                        chan, f"driver->{label(node)}", "in",
                        timed=False,
                    )
                    self._input_channels.append((edge, key))
                    descs.append(("chan", edge))
                elif isinstance(arg, ClassMethodNode):
                    src = placement[arg.actor_handle.actor_id.binary()]
                    chan = self._new_channel(src, node_placement)
                    # Direction "dag" (not the pipeline's
                    # "fwd"/"grad"): an exec loop's blocking input
                    # get also spans IDLE time between execute()
                    # calls, so these waits must not feed the
                    # doctor's straggler-stage heuristic — only the
                    # driver-paced pipeline streams do.
                    edge = Edge(
                        chan, f"{label(arg)}->{label(node)}", "dag"
                    )
                    out_chans[id(arg)].append(edge)
                    descs.append(("chan", edge))
                elif isinstance(arg, DAGNode):
                    raise TypeError(
                        f"unsupported arg node {type(arg).__name__}"
                    )
                else:
                    descs.append(("const", arg))
            if node._bound_kwargs:
                raise TypeError(
                    "compiled DAGs do not support kwargs in bind()"
                )
            in_descs[id(node)] = descs
        for out in outputs:
            src = placement[out.actor_handle.actor_id.binary()]
            chan = self._new_channel(src, driver_node)
            if isinstance(chan, TcpChannel):
                # Publish the driver's reader address NOW: a stage's
                # result put() must be able to complete into the TCP
                # backlog even if the driver never calls get()
                # (teardown-without-get must not wedge the exec loop
                # in rendezvous).
                chan.bind_reader()
            edge = Edge(
                chan, f"{label(out)}->driver", "out", timed=False
            )
            self._output_channels.append(edge)
            out_chans[id(out)].append(edge)

        # Start one persistent loop per actor.
        for node in actor_nodes:
            method = ActorMethod(node.actor_handle, DAG_LOOP_METHOD)
            ref = method.remote(
                node.method_name,
                in_descs[id(node)],
                out_chans[id(node)],
            )
            self._loop_refs.append(ref)

    def _new_channel(self, src_node: Optional[str],
                     dst_node: Optional[str]):
        if src_node is not None and src_node == dst_node:
            chan = ShmChannel(self._buffer)
        else:
            chan = TcpChannel(self._buffer)
        self._all_channels.append(chan)
        return chan

    @staticmethod
    def _driver_node_id() -> Optional[str]:
        from .._private.worker import global_worker

        worker = global_worker()
        node_id = getattr(worker, "node_id", None)
        return node_id.hex() if node_id is not None else None

    @staticmethod
    def _actor_placements(actor_nodes, timeout: float = 30.0):
        return wait_actor_placements(
            [n.actor_handle for n in actor_nodes], timeout=timeout
        )

    # -- execution -----------------------------------------------------
    def execute(
        self, value: Any, *, timeout: Optional[float] = 30.0
    ) -> CompiledDAGRef:
        # Input writes happen under a dedicated submit mutex (ordering
        # across concurrent executes) with a bounded put, so a stalled
        # or dead stage surfaces as ChannelTimeoutError instead of
        # blocking the state lock — which teardown() also needs.
        # Compute every projection BEFORE any channel write: a bad
        # input (missing key) must fail the whole execute, not leave
        # some stages fed and others starved.
        payloads = [
            (chan, value if key is _WHOLE else value[key])
            for chan, key in self._input_channels
        ]
        with self._submit_mutex:
            with self._lock:
                if self._torn_down:
                    raise RuntimeError("compiled DAG was torn down")
            # A previous execute() that timed out mid-fanout left some
            # channels without its record; deliver those first (its
            # DAG seq is already registered, so the streams must catch
            # up before a new record may enter any channel).
            self._drain_pending(timeout)
            with self._lock:
                seq = self._next_seq
                self._next_seq += 1
            for index, (chan, payload) in enumerate(payloads):
                try:
                    chan.put(("v", payload), timeout=timeout)  # rt: noqa[RT203] — _submit_mutex exists to serialize exactly this channel push (one in-flight execute by design)
                except ChannelTimeoutError as e:
                    # Park the undelivered tail: THIS channel resumes
                    # via the retry token (if the transport issued
                    # one — a partially-sent TCP record), the rest
                    # were never attempted. The seq is orphaned (the
                    # caller gets this exception, never a ref), so its
                    # output will be read-and-discarded.
                    self._pending_inputs = [
                        (chan, ("v", payload), getattr(e, "seq", None))
                    ] + [
                        (c, ("v", p), None)
                        for c, p in payloads[index + 1:]
                    ]
                    with self._lock:
                        self._orphan_seqs.add(seq)
                    raise
        return CompiledDAGRef(self, seq)

    def _drain_pending(self, timeout: Optional[float]) -> None:
        """Finish the fanout of a timed-out execute() exactly once per
        channel (caller holds the submit mutex). Raises
        ChannelTimeoutError (keeping the remaining tail parked) if a
        stage still isn't draining."""
        while self._pending_inputs:
            chan, record, token = self._pending_inputs[0]
            try:
                if token is not None:
                    # TcpChannel: resume the exact pending record.
                    chan.put(record, timeout=timeout, seq=token)  # rt: noqa[RT203] — drain runs under the submit mutex by design: pending records must flush in order
                else:
                    chan.put(record, timeout=timeout)  # rt: noqa[RT203] — drain runs under the submit mutex by design: pending records must flush in order
            except ChannelTimeoutError as e:
                self._pending_inputs[0] = (
                    chan, record, getattr(e, "seq", token)
                )
                raise
            self._pending_inputs.pop(0)

    def _read_result(self, seq: int, timeout: Optional[float]):
        """Channel records arrive in submission order. A future whose
        turn hasn't come reads (and caches) results for the earlier
        sequences until it reaches its own."""
        while True:
            with self._lock:
                if seq in self._results:
                    return self._results.pop(seq)
            with self._read_mutex:
                with self._lock:
                    if seq in self._results:
                        return self._results.pop(seq)
                    current = self._next_read_seq
                    if current > seq:
                        raise RuntimeError(
                            f"result {seq} was already consumed"
                        )
                # Commit the read-cursor bump only after the channel
                # read succeeds: a timeout here must leave the
                # seq->record mapping intact for retries.
                result = self._read_channels_once(timeout)
                with self._lock:
                    self._next_read_seq = current + 1
                    if current in self._orphan_seqs:
                        # Output of a timed-out execute(): no ref will
                        # ever claim it — discard instead of caching
                        # it forever.
                        self._orphan_seqs.discard(current)
                        continue
                    if current == seq:
                        return result
                    self._results[current] = result

    def _read_channels_once(self, timeout: Optional[float]):
        values = []
        error: Optional[BaseException] = None
        for chan in self._output_channels:
            tag, payload = chan.get(timeout=timeout)  # rt: noqa[RT203] — _read_mutex serializes exactly this channel read (results are consumed in order)
            if tag == "e":
                error = payload
            elif tag == "s":
                error = RuntimeError("compiled DAG stopped")
            else:
                values.append(payload)
        if error is not None:
            return error
        if isinstance(self._root, MultiOutputNode):
            return values
        return values[0]

    def teardown(self) -> None:
        """Stop every loop and release the channels; the actors return
        to normal method service."""
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        # Stop tokens go through the submit mutex like any execute
        # (bounded puts: a wedged stage can't hang teardown).
        with self._submit_mutex:
            # Best-effort: land any torn execute's records first so a
            # stage never sees stop-then-orphan out of order.
            try:
                self._drain_pending(2.0)
            except Exception:
                pass
            for chan, _key in self._input_channels:
                try:
                    chan.put(("s", None), timeout=5)  # rt: noqa[RT203] — teardown owns the submit mutex so no execute can interleave with the stop frame
                except Exception:
                    pass
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:
                pass
        for chan in self._all_channels:
            chan.close()
            chan.unlink()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
