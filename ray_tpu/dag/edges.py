"""Instrumented channel edges: named send/recv endpoints with per-edge
counters riding the metrics pipe.

A compiled DAG / MPMD pipeline is only diagnosable if a straggler
STAGE can be named the way the step doctor names a straggler rank —
which takes per-edge numbers: how many records hopped, how many bytes,
and how long each endpoint sat blocked in put/get. `Edge` wraps a
channel (ShmChannel or TcpChannel — anything with put_bytes/get_bytes/
close/unlink) with exactly that: local counters (cheap, always on,
returned by `stats()`) plus export through the PR 7 metrics pipe
(`dag_channel_hops_total` / `dag_channel_bytes_total` counters and
`dag_channel_send_wait_ms` / `dag_channel_recv_wait_ms` histograms,
labeled by edge), which the head folds into `doctor --json` under
``verdict["dag"]``.

Export is BATCHED off the hot path: a compiled-DAG hop is ~25-45 us
(MICROBENCH dag_hop_per_s) and per-op metric pushes would tax exactly
the number this instrumentation exists to defend — so counters flush
as accumulated deltas (every `_FLUSH_OPS` ops or `_FLUSH_S`), and
wait histograms sample 1-in-`_WAIT_SAMPLE` of sub-millisecond waits
while recording every wait >= 1 ms unconditionally (the bubble tail
is the diagnostic signal; the sub-ms noise floor is not).

Blocked time additionally bills the step-telemetry phases
``send_wait_ms`` / ``recv_wait_ms``, so an MPMD pipeline step's
bubble shows up attributed in the same per-(step, rank) records
gang-skew diagnosis already reads.

Edges are picklable: the wrapped channel re-attaches on the far side
and the counters start fresh there — each PROCESS counts its own
sends/recvs, which is what "which endpoint waited" needs.
"""

from __future__ import annotations

import time
from pickle import dumps as _dumps, loads as _loads
from time import monotonic as _mono
from typing import Any, Optional

#: Histogram bucket boundaries for send/recv wait (ms): the hot path
#: is tens of microseconds (native shm hop), the interesting tail is
#: schedule bubble — seconds.
_WAIT_BOUNDARIES = (0.1, 1.0, 5.0, 25.0, 100.0, 500.0, 2000.0)
_FLUSH_OPS = 64
_FLUSH_S = 0.25
_WAIT_SAMPLE = 16
#: Waits at/above this always reach the histogram, unsampled.
_WAIT_ALWAYS_MS = 1.0

_metrics_cache: dict = {}


def _metrics():
    """Lazily-built shared metric instances (one set per process —
    tags carry the edge identity)."""
    if not _metrics_cache:
        from ..util.metrics import Counter, Histogram

        _metrics_cache.update(
            hops=Counter(
                "dag_channel_hops_total",
                "records moved over a compiled-DAG/pipeline channel edge",
                tag_keys=("edge", "dir"),
            ),
            bytes=Counter(
                "dag_channel_bytes_total",
                "payload bytes moved over a channel edge",
                tag_keys=("edge", "dir"),
            ),
            send_wait=Histogram(
                "dag_channel_send_wait_ms",
                "time blocked in channel put (backpressure)",
                boundaries=_WAIT_BOUNDARIES,
                tag_keys=("edge", "dir"),
            ),
            recv_wait=Histogram(
                "dag_channel_recv_wait_ms",
                "time blocked in channel get (starvation; for "
                "compiled-DAG exec loops this INCLUDES idle time "
                "between invocations — see doctor's suspect gating)",
                boundaries=_WAIT_BOUNDARIES,
                tag_keys=("edge", "dir"),
            ),
        )
    return _metrics_cache


from .._private.step_telemetry import add_phase as _phase_add


def _phase(name: str, ms: float) -> None:
    """Bill blocked time into the step-telemetry phase bucket: the
    per-(step, rank) records the doctor/goodput read then attribute
    pipeline bubble the same way they attribute data_wait/h2d.
    Module-level import: this sits on the ~25 us compiled-DAG hop."""
    try:
        _phase_add(name, ms)
    except Exception:
        pass


def _worker_alive() -> bool:
    try:
        from .._private.worker import global_worker

        return global_worker() is not None
    except Exception:
        return False


class Edge:
    """One named, instrumented channel endpoint.

    `name` identifies the edge (e.g. ``"s0->s1:b0"``), `direction`
    the record stream riding it (``"fwd"``/``"grad"`` for pipelines,
    ``"in"``/``"out"`` for compiled-DAG IO). Wire format is pickled
    records — the compiled-DAG protocol tuples ride unchanged.

    ``timed=False`` is the lite mode for latency-critical edges whose
    blocked time is already the caller's own visible latency (the
    compiled-DAG driver's input/output hops, ~25 us each): hop/byte
    counters only, no clocks, no histograms — measured <0.5 us per
    op, vs ~2 us for the fully-timed path. Stage-to-stage edges stay
    fully timed: their ops are milliseconds of compute apart and
    their blocked time IS the pipeline bubble.
    """

    __slots__ = (
        "channel", "name", "direction", "timed",
        "hops_in", "hops_out", "bytes_in", "bytes_out",
        "send_wait_ms", "recv_wait_ms",
        "_unflushed_hops", "_unflushed_bytes", "_last_flush",
        "_op_seq",
    )

    def __init__(self, channel: Any, name: str,
                 direction: str = "fwd", *, timed: bool = True):
        self.channel = channel
        self.name = str(name)
        self.direction = str(direction)
        self.timed = bool(timed)
        self._reset_counters()
        # Export batching state (deltas since last flush).
        self._unflushed_hops = 0
        self._unflushed_bytes = 0
        self._last_flush = time.monotonic()
        self._op_seq = 0

    def _reset_counters(self) -> None:
        self.hops_in = 0
        self.hops_out = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.send_wait_ms = 0.0
        self.recv_wait_ms = 0.0

    # -- channel API, timed -------------------------------------------
    def put(self, record: Any, timeout: Optional[float] = None,
            **kw) -> None:
        payload = _dumps(record)
        if not self.timed:
            self.channel.put_bytes(payload, timeout=timeout, **kw)
        else:
            t0 = _mono()
            try:
                self.channel.put_bytes(
                    payload, timeout=timeout, **kw
                )
            finally:
                # Blocked time bills even when the put times out —
                # that IS the backpressure signal; hop/byte counts
                # only on delivery.
                waited = (_mono() - t0) * 1e3
                self.send_wait_ms += waited
                _phase("send_wait_ms", waited)
                seq = self._op_seq = self._op_seq + 1
                if waited >= _WAIT_ALWAYS_MS or not (
                    seq % _WAIT_SAMPLE
                ):
                    self._observe_wait("send_wait", waited)
        self.hops_out += 1
        nbytes = len(payload)
        self.bytes_out += nbytes
        self._unflushed_bytes += nbytes
        self._unflushed_hops += 1
        if self._unflushed_hops >= _FLUSH_OPS:
            self._flush_metrics()

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self.timed:
            payload = self.channel.get_bytes(timeout=timeout)
        else:
            t0 = _mono()
            try:
                payload = self.channel.get_bytes(timeout=timeout)
            finally:
                waited = (_mono() - t0) * 1e3
                self.recv_wait_ms += waited
                _phase("recv_wait_ms", waited)
                seq = self._op_seq = self._op_seq + 1
                if waited >= _WAIT_ALWAYS_MS or not (
                    seq % _WAIT_SAMPLE
                ):
                    self._observe_wait("recv_wait", waited)
        self.hops_in += 1
        nbytes = len(payload)
        self.bytes_in += nbytes
        self._unflushed_bytes += nbytes
        self._unflushed_hops += 1
        if self._unflushed_hops >= _FLUSH_OPS:
            self._flush_metrics()
        return _loads(payload)

    def put_value(self, value: Any,
                  timeout: Optional[float] = None) -> None:
        """Tagged-record convenience used by the MPMD pipeline:
        ``("v", value)``; peers distinguish data from the
        compiled-DAG-style error/stop records."""
        self.put(("v", value), timeout=timeout)

    def get_value(self, timeout: Optional[float] = None) -> Any:
        tag, payload = self.get(timeout=timeout)
        if tag == "e":
            raise payload if isinstance(
                payload, BaseException
            ) else RuntimeError(str(payload))
        if tag == "s":
            from .channels import ChannelClosedError

            raise ChannelClosedError(f"edge {self.name} stopped")
        return payload

    # -- batched metric export ----------------------------------------
    def _observe_wait(self, which: str, waited_ms: float) -> None:
        """Off the hot path: the caller already sampled (1-in-N of
        sub-ms waits; every wait >= 1 ms). Piggybacks the time-based
        counter flush so idle-but-trickling edges still export."""
        if not _worker_alive():
            return
        try:
            _metrics()[which].observe(
                waited_ms,
                {"edge": self.name, "dir": self.direction},
            )
        except Exception:
            pass
        if time.monotonic() - self._last_flush >= _FLUSH_S:
            self._flush_metrics()

    def _flush_metrics(self) -> None:
        # No runtime session: the deltas can never be exported — drop
        # them (local stats() counters are unaffected) instead of
        # re-attempting on every op.
        if self._unflushed_hops and _worker_alive():
            try:
                m = _metrics()
                tags = {"edge": self.name, "dir": self.direction}
                m["hops"].inc(self._unflushed_hops, tags)
                if self._unflushed_bytes:
                    m["bytes"].inc(self._unflushed_bytes, tags)
            except Exception:
                pass
        self._unflushed_hops = 0
        self._unflushed_bytes = 0
        self._last_flush = time.monotonic()

    # -- passthrough ---------------------------------------------------
    def close(self) -> None:
        self._flush_metrics()
        self.channel.close()

    def unlink(self) -> None:
        unlink = getattr(self.channel, "unlink", None)
        if unlink is not None:
            unlink()

    def stats(self) -> dict:
        """This endpoint's counters since construction (or the last
        `take_stats`)."""
        return {
            "edge": self.name,
            "dir": self.direction,
            "hops_in": self.hops_in,
            "hops_out": self.hops_out,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "send_wait_ms": round(self.send_wait_ms, 3),
            "recv_wait_ms": round(self.recv_wait_ms, 3),
        }

    def take_stats(self) -> dict:
        """stats() then reset — per-step deltas for pipeline
        drivers. The metric-pipe deltas flush on their own cadence."""
        self._flush_metrics()
        out = self.stats()
        self._reset_counters()
        return out

    def __reduce__(self):
        return (
            _rebuild_edge,
            (self.channel, self.name, self.direction, self.timed),
        )


def _rebuild_edge(channel, name, direction, timed=True):
    return Edge(channel, name, direction, timed=timed)
