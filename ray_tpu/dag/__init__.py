"""Lazy + compiled DAG API (reference: python/ray/dag/)."""

from .channels import ShmChannel
from .compiled import CompiledDAG, CompiledDAGRef
from .edges import Edge
from .dag_node import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    experimental_compile,
)

__all__ = [
    "DAGNode",
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
    "FunctionNode",
    "ClassMethodNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "ShmChannel",
    "Edge",
    "experimental_compile",
]
