"""ctypes binding to the native arena store.

Builds libray_tpu_store.so on first import if the toolchain is
available (make/g++ are part of the supported image); callers fall
back to the pure-Python per-segment store when the library can't load
(reference split: plasma is C++, its client rides in every worker).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
#: RT_NATIVE_SO overrides the library path (the sanitizer test points
#: it at an ASan/UBSan-instrumented build; make is skipped then). One
#: import-time snapshot drives BOTH the path and the skip-make
#: decision so they can never disagree.
_SO_OVERRIDE = os.environ.get("RT_NATIVE_SO")
_SO = _SO_OVERRIDE or os.path.join(_DIR, "libray_tpu_store.so")
_build_lock = threading.Lock()  # rt: noqa[RT004] — held only inside load_library(), never across fork
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

OID_BYTES = 20

RTS_OK = 0
RTS_ERR_EXISTS = -2
RTS_ERR_FULL = -3
RTS_ERR_MISSING = -4
RTS_ERR_STATE = -5


def load_library() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native store; None on failure."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        # Always invoke make: it no-ops when the .so is fresh and
        # rebuilds when store.cc changed (a stale .so must never load).
        # An RT_NATIVE_SO override is loaded as-is (pre-built).
        if _SO_OVERRIDE is None:
            try:
                subprocess.run(  # rt: noqa[RT203] — build-once gate: holding _build_lock across the build IS the serialization
                    ["make", "-C", _DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                if not os.path.exists(_SO):
                    _load_failed = True
                    return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _load_failed = True
            return None
        lib.rts_open.restype = ctypes.c_void_p
        lib.rts_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.rts_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rts_base.argtypes = [ctypes.c_void_p]
        lib.rts_create.restype = ctypes.c_int64
        lib.rts_create.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.rts_seal.restype = ctypes.c_int
        lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_lookup.restype = ctypes.c_int64
        lib.rts_lookup.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
        ]
        lib.rts_pin.restype = ctypes.c_int64
        lib.rts_pin.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rts_seal_pinned.restype = ctypes.c_int64
        lib.rts_seal_pinned.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rts_unpin_idx.restype = ctypes.c_int
        lib.rts_unpin_idx.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.rts_reap_dead_pins.restype = ctypes.c_int
        lib.rts_reap_dead_pins.argtypes = [ctypes.c_void_p]
        lib.rts_untracked_pins.restype = ctypes.c_uint64
        lib.rts_untracked_pins.argtypes = [ctypes.c_void_p]
        lib.rts_delete.restype = ctypes.c_int
        lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_stats.restype = ctypes.c_int
        lib.rts_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rts_close.restype = None
        lib.rts_close.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_char_p,
        ]
        try:  # added after v1 .so builds; staleness check rebuilds,
            # but never let a stale binary break the whole store.
            lib.rts_load_acq_u64.restype = ctypes.c_uint64
            lib.rts_load_acq_u64.argtypes = [ctypes.c_void_p]
            lib.rts_store_rel_u64.restype = None
            lib.rts_store_rel_u64.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
            ]
        except AttributeError:
            pass
        try:  # futex doorbell (added with the DAG channel wakeups)
            lib.rts_futex_wait_u32.restype = ctypes.c_int
            lib.rts_futex_wait_u32.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint32,
                ctypes.c_int64,
            ]
            lib.rts_futex_wake.restype = ctypes.c_int
            lib.rts_futex_wake.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int,
            ]
        except AttributeError:
            pass
        try:  # whole-op ring put/get (dag/channels.py hot path)
            lib.rts_chan_put.restype = ctypes.c_int
            lib.rts_chan_put.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.c_int64,
            ]
            lib.rts_chan_get.restype = ctypes.c_int64
            lib.rts_chan_get.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_int64,
            ]
        except AttributeError:
            pass
        _lib = lib
        return _lib


class NativeArena:
    """Thin OO wrapper over the C surface (one arena per node)."""

    def __init__(
        self,
        path: str,
        capacity: int,
        num_slots: int = 65536,
        create: bool = True,
    ):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        self._path = path.encode()
        self._handle = lib.rts_open(
            self._path, capacity, num_slots, 1 if create else 0
        )
        if not self._handle:
            raise RuntimeError(f"rts_open failed for {path}")
        self._base = ctypes.cast(
            lib.rts_base(self._handle), ctypes.c_void_p
        ).value
        self._closed = False
        # Serializes native entry points against close(): a bare
        # `_closed` flag check is a TOCTOU — close() unmapping the
        # arena while another thread (daemon heartbeat reaper, RPC
        # handler) is inside an rts_* call is a segfault. RLock, not
        # Lock: unpin finalizers fire from GC at arbitrary points,
        # including while the same thread holds the lock.
        self._call_lock = threading.RLock()

    @classmethod
    def attach(cls, path: str) -> "NativeArena":
        """Attach to ANOTHER process's arena file (same host), sizing
        the mapping from the creator's on-disk header — the attacher
        need not know the creator's capacity/num_slots config. Used by
        the daemon's same-host object-transfer fast path (plasma
        analog: clients mmap the store and read under a pin)."""
        import struct

        with open(path, "rb") as f:
            header = f.read(40)  # magic,capacity,used,lru_clock,slots
        if len(header) < 40:
            raise RuntimeError(f"truncated arena header: {path}")
        magic, capacity, _used, _clock, num_slots = struct.unpack(
            "<QQQQI", header[:36]
        )
        if magic != 0x5254535052455632:  # store.cc kMagic
            raise RuntimeError(f"not an arena file: {path}")
        return cls(path, capacity, num_slots=num_slots, create=False)

    @staticmethod
    def _key(oid: bytes) -> bytes:
        if len(oid) > OID_BYTES:
            raise ValueError("oid too long")
        return oid.ljust(OID_BYTES, b"\0")

    def _view(self, offset: int, size: int) -> memoryview:
        address = self._base + offset
        buf = (ctypes.c_char * size).from_address(address)
        return memoryview(buf).cast("B")

    def create(self, oid: bytes, size: int):
        """Returns (writable memoryview, [evicted oids])."""
        evicted = ctypes.create_string_buffer(OID_BYTES * 64)
        n_evicted = ctypes.c_int(0)
        with self._call_lock:
            if self._closed:
                raise MemoryError("arena closed")
            offset = self._lib.rts_create(
                self._handle,
                self._key(oid),
                max(size, 1),
                evicted,
                64,
                ctypes.byref(n_evicted),
            )
        if offset == RTS_ERR_EXISTS:
            raise ValueError(f"object {oid.hex()} already exists")
        if offset < 0:
            raise MemoryError(f"arena full (err {offset})")
        ids = [
            evicted.raw[i * OID_BYTES : (i + 1) * OID_BYTES]
            for i in range(n_evicted.value)
        ]
        return self._view(offset, max(size, 1))[:size], ids

    def seal(self, oid: bytes) -> None:
        with self._call_lock:
            if self._closed:
                raise KeyError("arena closed")
            rc = self._lib.rts_seal(self._handle, self._key(oid))
        if rc != RTS_OK:
            raise KeyError(f"seal({oid.hex()}) -> {rc}")

    def get(self, oid: bytes, sealed_only: bool = True):
        size = ctypes.c_uint64(0)
        with self._call_lock:
            if self._closed:
                return None
            offset = self._lib.rts_lookup(
                self._handle,
                self._key(oid),
                ctypes.byref(size),
                1 if sealed_only else 0,
            )
            if offset < 0:
                return None
            # View built inside the critical section: offset is only
            # meaningful while nothing can close/delete in between
            # (same atomic lookup+view shape as try_pin).
            return self._view(offset, max(int(size.value), 1))[
                : int(size.value)
            ]

    def contains(self, oid: bytes) -> bool:
        return self.get(oid) is not None

    def try_pin(self, oid: bytes):
        """Atomically pin the sealed slot holding `oid` and return
        (slot_index, zero-copy view) — or None if absent/unsealed.
        Offset and size come back from the same critical section as
        the pin, so the view always maps the pinned slot (a separate
        lookup could race with delete + re-create of the oid)."""
        offset = ctypes.c_uint64(0)
        size = ctypes.c_uint64(0)
        with self._call_lock:
            if self._closed:
                return None
            index = self._lib.rts_pin(
                self._handle,
                self._key(oid),
                ctypes.byref(offset),
                ctypes.byref(size),
            )
            if index < 0:
                return None
            n = int(size.value)
            return (
                int(index),
                self._view(int(offset.value), max(n, 1))[:n],
            )

    def seal_pinned(self, oid: bytes):
        """Seal the CREATING slot and take a reader pin in one
        critical section (see rts_seal_pinned: closes the window where
        a freshly sealed, pin-less slot is an LRU victim before its
        owner can protect it). Returns (slot_index, view) or None."""
        offset = ctypes.c_uint64(0)
        size = ctypes.c_uint64(0)
        with self._call_lock:
            if self._closed:
                return None
            index = self._lib.rts_seal_pinned(
                self._handle,
                self._key(oid),
                ctypes.byref(offset),
                ctypes.byref(size),
            )
            if index < 0:
                return None
            n = int(size.value)
            return (
                int(index),
                self._view(int(offset.value), max(n, 1))[:n],
            )

    def unpin_idx(self, index: int) -> None:
        # Reader-pin finalizers can outlive close() (weakref.finalize on
        # fetched values fires at GC time); touching the unmapped arena
        # then would segfault.
        with self._call_lock:
            if self._closed:
                return
            self._lib.rts_unpin_idx(self._handle, index)

    def reap_dead_pins(self) -> int:
        """Release pins whose owning process has died (plasma's
        disconnect-reclaim analog); returns pins reclaimed."""
        with self._call_lock:
            if self._closed:
                return 0
            return int(self._lib.rts_reap_dead_pins(self._handle))

    def delete(self, oid: bytes) -> bool:
        with self._call_lock:
            if self._closed:
                return False
            return (
                self._lib.rts_delete(self._handle, self._key(oid))
                == RTS_OK
            )

    def stats(self) -> dict:
        capacity = ctypes.c_uint64(0)
        used = ctypes.c_uint64(0)
        num = ctypes.c_uint64(0)
        with self._call_lock:
            if self._closed:
                return {
                    "capacity": 0, "used": 0, "num_objects": 0,
                    "untracked_pins": 0,
                }
            self._lib.rts_stats(
                self._handle,
                ctypes.byref(capacity),
                ctypes.byref(used),
                ctypes.byref(num),
            )
            untracked = int(self._lib.rts_untracked_pins(self._handle))
        return {
            "capacity": capacity.value,
            "used": used.value,
            "num_objects": num.value,
            "untracked_pins": untracked,
        }

    def close(self, unlink: bool = False) -> None:
        with self._call_lock:
            if self._closed:
                return
            self._closed = True
            self._lib.rts_close(
                self._handle, 1 if unlink else 0, self._path
            )
