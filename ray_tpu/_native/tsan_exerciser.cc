// ThreadSanitizer exerciser for the shared-memory arena (store.cc).
//
// Completes the sanitizer trio the reference maintains for its C++
// core (SURVEY §5.2): ASan/UBSan sweep the API single-threaded
// (tests/test_sanitizers.py drives the Python binding under a
// preloaded runtime); THIS binary hammers one arena from many
// threads — and optionally several forked processes — under
// -fsanitize=thread, which needs an instrumented main() (TSan does
// not support LD_PRELOAD into an uninstrumented interpreter, so the
// exerciser is a standalone program rather than a Python script).
//
// Shape: N threads x M iterations of randomized create / write /
// seal(+pinned) / pin+read / delete / stats / reap against a small
// arena (eviction pressure guaranteed: the oid working set exceeds
// capacity). Payload writes happen OUTSIDE the arena mutex by design
// — the happens-before chain create(lock) -> write -> seal(lock) ->
// pin(lock) -> read is exactly what TSan verifies. Forked children
// run before any thread starts (TSan restriction) and exercise the
// PROCESS-SHARED robust mutex across address spaces.
//
// Build (tests/test_sanitizers.py does this on the fly; also
// `make -C ray_tpu/_native tsan`):
//   g++ -O1 -g -std=c++17 -fsanitize=thread \
//       store.cc tsan_exerciser.cc -o store_tsan_exerciser -lpthread
//
// Usage: store_tsan_exerciser <arena-path> [threads] [iters] [forks]
// Exits 0 and prints TSAN-SWEEP-OK when the sweep finishes with
// consistent stats; TSan itself aborts nonzero on any race.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <pthread.h>
#include <sys/wait.h>
#include <unistd.h>

extern "C" {
void* rts_open(const char* path, uint64_t capacity, uint32_t num_slots,
               int create);
uint8_t* rts_base(void* handle);
int64_t rts_create(void* handle, const uint8_t* oid, uint64_t size,
                   uint8_t* evicted_out, int max_evicted, int* n_evicted);
int rts_seal(void* handle, const uint8_t* oid);
int64_t rts_seal_pinned(void* handle, const uint8_t* oid,
                        uint64_t* offset_out, uint64_t* size_out);
int64_t rts_lookup(void* handle, const uint8_t* oid, uint64_t* size_out,
                   int sealed_only);
int64_t rts_pin(void* handle, const uint8_t* oid, uint64_t* offset_out,
                uint64_t* size_out);
int rts_unpin_idx(void* handle, int32_t index);
int rts_reap_dead_pins(void* handle);
uint64_t rts_untracked_pins(void* handle);
int rts_delete(void* handle, const uint8_t* oid);
int rts_stats(void* handle, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects);
void rts_close(void* handle, int unlink_file, const char* path);
}

namespace {

constexpr uint32_t kOidBytes = 20;
constexpr uint64_t kCapacity = 1 << 20;  // 1 MiB: guarantees eviction
constexpr uint32_t kSlots = 1024;
constexpr int kOidSpace = 64;  // working set of object ids

struct ThreadArgs {
  void* handle;
  uint8_t* heap;
  uint64_t seed;
  int iters;
  long errors;  // impossible return codes (not contention outcomes)
};

void make_oid(int i, uint8_t* out) {
  memset(out, 0, kOidBytes);
  snprintf(reinterpret_cast<char*>(out), kOidBytes, "oid-%04d", i);
}

uint64_t next_rand(uint64_t* state) {  // splitmix64: deterministic,
  *state += 0x9e3779b97f4a7c15ULL;     // no shared libc rand() state
  uint64_t z = *state;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

void* hammer(void* argp) {
  ThreadArgs* args = static_cast<ThreadArgs*>(argp);
  uint64_t rng = args->seed;
  uint8_t oid[kOidBytes];
  uint8_t evicted[kOidBytes * 64];
  volatile uint64_t sink = 0;  // keep payload reads alive
  for (int i = 0; i < args->iters; ++i) {
    uint64_t r = next_rand(&rng);
    make_oid(static_cast<int>(r % kOidSpace), oid);
    uint64_t op = (r >> 8) % 100;
    if (op < 40) {
      // create -> fill payload (outside the lock: the interesting
      // part) -> seal; every third creation uses the combined
      // seal_pinned and reads its own bytes back under the pin.
      uint64_t size = 64 + ((r >> 16) % 4000);
      int n_evicted = 0;
      int64_t offset = rts_create(args->handle, oid, size, evicted, 64,
                                  &n_evicted);
      if (offset >= 0) {
        memset(args->heap + offset, static_cast<int>(r & 0xff),
               static_cast<size_t>(size));
        if (op % 3 == 0) {
          uint64_t poff = 0, psize = 0;
          int64_t index =
              rts_seal_pinned(args->handle, oid, &poff, &psize);
          if (index >= 0) {
            sink += args->heap[poff] + args->heap[poff + psize - 1];
            rts_unpin_idx(args->handle, static_cast<int32_t>(index));
          }
        } else {
          rts_seal(args->handle, oid);
        }
      } else if (offset != -2 && offset != -3) {
        ++args->errors;  // EXISTS/FULL are expected under contention
      }
    } else if (op < 70) {
      uint64_t poff = 0, psize = 0;
      int64_t index = rts_pin(args->handle, oid, &poff, &psize);
      if (index >= 0) {
        // Read while pinned: first/middle/last byte of the payload.
        sink += args->heap[poff] + args->heap[poff + psize / 2] +
                args->heap[poff + psize - 1];
        rts_unpin_idx(args->handle, static_cast<int32_t>(index));
      }
    } else if (op < 85) {
      rts_delete(args->handle, oid);
    } else if (op < 95) {
      uint64_t size = 0;
      rts_lookup(args->handle, oid, &size, 1);
      uint64_t cap = 0, used = 0, num = 0;
      rts_stats(args->handle, &cap, &used, &num);
      if (used > cap) ++args->errors;
    } else {
      rts_reap_dead_pins(args->handle);
      rts_untracked_pins(args->handle);
    }
  }
  return nullptr;
}

// Run the threaded sweep in the current process; returns error count.
long run_threads(void* handle, int threads, int iters, uint64_t salt) {
  ThreadArgs* args = new ThreadArgs[threads];
  pthread_t* tids = new pthread_t[threads];
  uint8_t* heap = rts_base(handle);
  for (int t = 0; t < threads; ++t) {
    args[t] = ThreadArgs{handle, heap,
                         salt * 1000003ULL + static_cast<uint64_t>(t) + 1,
                         iters, 0};
    if (pthread_create(&tids[t], nullptr, hammer, &args[t]) != 0) {
      fprintf(stderr, "pthread_create failed\n");
      exit(2);
    }
  }
  long errors = 0;
  for (int t = 0; t < threads; ++t) {
    pthread_join(tids[t], nullptr);
    errors += args[t].errors;
  }
  delete[] args;
  delete[] tids;
  return errors;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr,
            "usage: %s <arena-path> [threads] [iters] [forks]\n",
            argv[0]);
    return 2;
  }
  const char* path = argv[1];
  int threads = argc > 2 ? atoi(argv[2]) : 8;
  int iters = argc > 3 ? atoi(argv[3]) : 3000;
  int forks = argc > 4 ? atoi(argv[4]) : 2;

  void* handle = rts_open(path, kCapacity, kSlots, /*create=*/1);
  if (handle == nullptr) {
    fprintf(stderr, "rts_open(%s) failed\n", path);
    return 2;
  }

  // Fork BEFORE spawning any thread (TSan supports single-threaded
  // fork); children inherit the MAP_SHARED arena, so the pshared
  // robust mutex is contended across real address spaces.
  pid_t kids[16];
  int nkids = 0;
  long errors = 0;
  if (forks > 16) forks = 16;
  for (int f = 0; f < forks; ++f) {
    pid_t pid = fork();
    if (pid < 0) {
      // A failed fork must not reach waitpid(-1) (it would reap an
      // arbitrary child and corrupt the pass/fail accounting).
      fprintf(stderr, "fork %d failed\n", f);
      ++errors;
      continue;
    }
    if (pid == 0) {
      long child_errors =
          run_threads(handle, threads, iters, 100 + static_cast<uint64_t>(f));
      _exit(child_errors == 0 ? 0 : 3);
    }
    kids[nkids++] = pid;
  }

  errors += run_threads(handle, threads, iters, 7);

  for (int f = 0; f < nkids; ++f) {
    int status = 0;
    waitpid(kids[f], &status, 0);
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 0) {
      fprintf(stderr, "child %d failed (status %d)\n", f, status);
      ++errors;
    }
  }

  uint64_t cap = 0, used = 0, num = 0;
  rts_stats(handle, &cap, &used, &num);
  if (used > cap) {
    fprintf(stderr, "inconsistent stats: used %lu > capacity %lu\n",
            static_cast<unsigned long>(used),
            static_cast<unsigned long>(cap));
    ++errors;
  }
  rts_close(handle, /*unlink_file=*/1, path);
  if (errors != 0) {
    fprintf(stderr, "%ld errors\n", errors);
    return 3;
  }
  printf("TSAN-SWEEP-OK threads=%d iters=%d forks=%d objects=%lu\n",
         threads, iters, forks, static_cast<unsigned long>(num));
  return 0;
}
