// Shared-memory arena object store (plasma analog).
//
// Reference: src/ray/object_manager/plasma/ — an mmap'd arena
// (plasma/dlmalloc.cc) holding immutable objects behind an object
// index with create/seal/get/release/delete + LRU eviction
// (object_lifecycle_manager.h, eviction_policy.h). This is the
// TPU-native C++ equivalent: one arena file per node under /dev/shm,
// a process-shared mutex guarding a fixed-slot index + first-fit
// free list with coalescing, and 64-byte aligned payloads so mapped
// buffers feed jax.numpy/dlpack zero-copy.
//
// Exported as a C ABI for the ctypes binding in
// ray_tpu/_native/__init__.py (the environment provides no pybind11;
// ctypes over a stable C surface is the supported binding path).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254535052455631ULL;  // "RTSTOREV1"
constexpr uint32_t kOidBytes = 20;
constexpr uint32_t kAlign = 64;

enum SlotState : uint32_t {
  kFree = 0,
  kCreating = 1,
  kSealed = 2,
};

struct Slot {
  uint8_t oid[kOidBytes];
  uint32_t state;
  uint32_t pins;
  uint64_t offset;  // into the data heap
  uint64_t size;
  uint64_t lru_tick;
};

// Free-list node stored inside the header's node pool (not in the data
// heap itself, so payload memory stays payload-only).
struct FreeNode {
  uint64_t offset;
  uint64_t size;
  int32_t next;  // index into node pool, -1 == end
  int32_t in_use;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // data heap bytes
  uint64_t used;           // allocated bytes
  uint64_t lru_clock;
  uint32_t num_slots;
  uint32_t num_free_nodes;
  int32_t free_head;       // free-list head (node index)
  uint32_t initialized;
  pthread_mutex_t mutex;
  // Slot table and node pool follow; data heap after that.
};

struct Handle {
  int fd;
  uint8_t* map;
  uint64_t map_size;
  Header* header;
  Slot* slots;
  FreeNode* nodes;
  uint8_t* heap;
};

uint64_t AlignUp(uint64_t v, uint64_t a) { return (v + a - 1) / a * a; }

Slot* FindSlot(Handle* h, const uint8_t* oid) {
  // Linear probe from the oid's hash position.
  uint64_t hash = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kOidBytes; ++i) {
    hash = (hash ^ oid[i]) * 1099511628211ULL;
  }
  const uint32_t n = h->header->num_slots;
  for (uint32_t probe = 0; probe < n; ++probe) {
    Slot* slot = &h->slots[(hash + probe) % n];
    if (slot->state != kFree &&
        memcmp(slot->oid, oid, kOidBytes) == 0) {
      return slot;
    }
  }
  return nullptr;
}

Slot* FindEmptySlot(Handle* h, const uint8_t* oid) {
  uint64_t hash = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kOidBytes; ++i) {
    hash = (hash ^ oid[i]) * 1099511628211ULL;
  }
  const uint32_t n = h->header->num_slots;
  for (uint32_t probe = 0; probe < n; ++probe) {
    Slot* slot = &h->slots[(hash + probe) % n];
    if (slot->state == kFree) return slot;
  }
  return nullptr;
}

int32_t AllocNode(Handle* h) {
  for (uint32_t i = 0; i < h->header->num_free_nodes; ++i) {
    if (!h->nodes[i].in_use) {
      h->nodes[i].in_use = 1;
      return static_cast<int32_t>(i);
    }
  }
  return -1;
}

// First-fit allocation from the free list.
int64_t HeapAlloc(Handle* h, uint64_t size) {
  Header* hd = h->header;
  int32_t prev = -1;
  int32_t cur = hd->free_head;
  while (cur >= 0) {
    FreeNode* node = &h->nodes[cur];
    if (node->size >= size) {
      uint64_t offset = node->offset;
      if (node->size == size) {
        if (prev < 0) hd->free_head = node->next;
        else h->nodes[prev].next = node->next;
        node->in_use = 0;
      } else {
        node->offset += size;
        node->size -= size;
      }
      hd->used += size;
      return static_cast<int64_t>(offset);
    }
    prev = cur;
    cur = node->next;
  }
  return -1;
}

// Insert a free range, merging neighbors (offset-sorted list).
void HeapFree(Handle* h, uint64_t offset, uint64_t size) {
  Header* hd = h->header;
  hd->used -= size;
  int32_t prev = -1;
  int32_t cur = hd->free_head;
  while (cur >= 0 && h->nodes[cur].offset < offset) {
    prev = cur;
    cur = h->nodes[cur].next;
  }
  // Merge with previous?
  if (prev >= 0 &&
      h->nodes[prev].offset + h->nodes[prev].size == offset) {
    h->nodes[prev].size += size;
    // Merge previous with current?
    if (cur >= 0 && h->nodes[prev].offset + h->nodes[prev].size ==
                        h->nodes[cur].offset) {
      h->nodes[prev].size += h->nodes[cur].size;
      h->nodes[prev].next = h->nodes[cur].next;
      h->nodes[cur].in_use = 0;
    }
    return;
  }
  // Merge with current?
  if (cur >= 0 && offset + size == h->nodes[cur].offset) {
    h->nodes[cur].offset = offset;
    h->nodes[cur].size += size;
    return;
  }
  int32_t fresh = AllocNode(h);
  if (fresh < 0) return;  // node pool exhausted: leak range (rare)
  h->nodes[fresh].offset = offset;
  h->nodes[fresh].size = size;
  h->nodes[fresh].next = cur;
  if (prev < 0) hd->free_head = fresh;
  else h->nodes[prev].next = fresh;
}

void DeleteSlotLocked(Handle* h, Slot* slot) {
  HeapFree(h, slot->offset, AlignUp(slot->size ? slot->size : 1, kAlign));
  slot->state = kFree;
  slot->pins = 0;
}

// Evict the single LRU sealed+unpinned object; false if none exists.
bool EvictOneLocked(Handle* h, uint8_t* evicted_out, int* count,
                    int max_evicted) {
  if (*count >= max_evicted) return false;
  Header* hd = h->header;
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < hd->num_slots; ++i) {
    Slot* slot = &h->slots[i];
    if (slot->state == kSealed && slot->pins == 0 &&
        (victim == nullptr || slot->lru_tick < victim->lru_tick)) {
      victim = slot;
    }
  }
  if (victim == nullptr) return false;
  memcpy(evicted_out + *count * kOidBytes, victim->oid, kOidBytes);
  ++(*count);
  DeleteSlotLocked(h, victim);
  return true;
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->header->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock (e.g. the OOM killer SIGKILLed
      // a worker mid-create). The shared state may hold a CREATING
      // slot that will never seal — acceptable garbage — but the
      // mutex must be marked consistent or it becomes permanently
      // unusable (ENOTRECOVERABLE) for every process.
      pthread_mutex_consistent(&h_->header->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->header->mutex); }

 private:
  Handle* h_;
};

}  // namespace

extern "C" {

// Error codes.
#define RTS_OK 0
#define RTS_ERR_EXISTS -2
#define RTS_ERR_FULL -3
#define RTS_ERR_MISSING -4
#define RTS_ERR_STATE -5
#define RTS_ERR_SYS -6

void* rts_open(const char* path, uint64_t capacity, uint32_t num_slots,
               int create) {
  const uint64_t node_pool = num_slots;  // one free node per slot
  const uint64_t meta_size =
      AlignUp(sizeof(Header) + num_slots * sizeof(Slot) +
                  node_pool * sizeof(FreeNode),
              kAlign);
  const uint64_t total = meta_size + capacity;
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (create) {
    if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
      close(fd);
      return nullptr;
    }
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < total) {
    close(fd);
    return nullptr;
  }
  uint8_t* map = static_cast<uint8_t*>(mmap(
      nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle;
  h->fd = fd;
  h->map = map;
  h->map_size = total;
  h->header = reinterpret_cast<Header*>(map);
  h->slots = reinterpret_cast<Slot*>(map + sizeof(Header));
  h->nodes = reinterpret_cast<FreeNode*>(
      map + sizeof(Header) + num_slots * sizeof(Slot));
  h->heap = map + meta_size;
  if (create && h->header->initialized != 1) {
    Header* hd = h->header;
    memset(map, 0, meta_size);
    hd->magic = kMagic;
    hd->capacity = capacity;
    hd->used = 0;
    hd->lru_clock = 0;
    hd->num_slots = num_slots;
    hd->num_free_nodes = static_cast<uint32_t>(node_pool);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hd->mutex, &attr);
    h->nodes[0].offset = 0;
    h->nodes[0].size = capacity;
    h->nodes[0].next = -1;
    h->nodes[0].in_use = 1;
    hd->free_head = 0;
    __sync_synchronize();
    hd->initialized = 1;
  }
  if (h->header->magic != kMagic) {
    munmap(map, total);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

uint8_t* rts_base(void* handle) {
  return static_cast<Handle*>(handle)->heap;
}

int64_t rts_create(void* handle, const uint8_t* oid, uint64_t size,
                   uint8_t* evicted_out, int max_evicted,
                   int* n_evicted) {
  Handle* h = static_cast<Handle*>(handle);
  uint64_t need = AlignUp(size ? size : 1, kAlign);
  Locker lock(h);
  *n_evicted = 0;
  if (FindSlot(h, oid) != nullptr) return RTS_ERR_EXISTS;
  if (need > h->header->capacity) return RTS_ERR_FULL;
  // Keep evicting LRU victims until a contiguous range exists —
  // byte-count checks alone miss fragmentation (freed neighbors must
  // coalesce before a large allocation fits).
  int64_t offset = HeapAlloc(h, need);
  while (offset < 0 &&
         EvictOneLocked(h, evicted_out, n_evicted, max_evicted)) {
    offset = HeapAlloc(h, need);
  }
  if (offset < 0) return RTS_ERR_FULL;
  Slot* slot = FindEmptySlot(h, oid);
  if (slot == nullptr) {
    HeapFree(h, static_cast<uint64_t>(offset), need);
    return RTS_ERR_FULL;
  }
  memcpy(slot->oid, oid, kOidBytes);
  slot->state = kCreating;
  slot->pins = 0;
  slot->offset = static_cast<uint64_t>(offset);
  slot->size = size;
  slot->lru_tick = ++h->header->lru_clock;
  return offset;
}

int rts_seal(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->state != kCreating) return RTS_ERR_STATE;
  slot->state = kSealed;
  return RTS_OK;
}

// Looks up a SEALED object; returns offset, fills size. -4 if absent
// or unsealed (sealed_only=0 accepts CREATING too).
int64_t rts_lookup(void* handle, const uint8_t* oid, uint64_t* size_out,
                   int sealed_only) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (sealed_only && slot->state != kSealed) return RTS_ERR_MISSING;
  slot->lru_tick = ++h->header->lru_clock;
  *size_out = slot->size;
  return static_cast<int64_t>(slot->offset);
}

int rts_pin(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  slot->pins += 1;
  return RTS_OK;
}

int rts_unpin(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->pins > 0) slot->pins -= 1;
  return RTS_OK;
}

int rts_delete(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  DeleteSlotLocked(h, slot);
  return RTS_OK;
}

int rts_stats(void* handle, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  *capacity = h->header->capacity;
  *used = h->header->used;
  uint64_t count = 0;
  for (uint32_t i = 0; i < h->header->num_slots; ++i) {
    if (h->slots[i].state != kFree) ++count;
  }
  *num_objects = count;
  return RTS_OK;
}

void rts_close(void* handle, int unlink_file, const char* path) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->map, h->map_size);
  close(h->fd);
  if (unlink_file && path != nullptr) unlink(path);
  delete h;
}

}  // extern "C"
