// Shared-memory arena object store (plasma analog).
//
// Reference: src/ray/object_manager/plasma/ — an mmap'd arena
// (plasma/dlmalloc.cc) holding immutable objects behind an object
// index with create/seal/get/release/delete + LRU eviction
// (object_lifecycle_manager.h, eviction_policy.h). This is the
// TPU-native C++ equivalent: one arena file per node under /dev/shm,
// a process-shared mutex guarding a fixed-slot index + first-fit
// free list with coalescing, and 64-byte aligned payloads so mapped
// buffers feed jax.numpy/dlpack zero-copy.
//
// Exported as a C ABI for the ctypes binding in
// ray_tpu/_native/__init__.py (the environment provides no pybind11;
// ctypes over a stable C surface is the supported binding path).

#include <cerrno>
#include <csignal>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <linux/futex.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254535052455632ULL;  // "RTSTOREV2"
constexpr uint32_t kOidBytes = 20;
constexpr uint32_t kAlign = 64;
// Distinct live reader pids tracked per slot; a pin beyond this is
// still taken (reader safety first) but lands in untracked_pins and
// cannot be crash-reclaimed, so keep headroom above the typical
// workers-per-host concurrency on one hot object.
constexpr uint32_t kPinRecsPerSlot = 4;

enum SlotState : uint32_t {
  kFree = 0,
  kCreating = 1,
  kSealed = 2,
  // Deleted while readers still hold pins: invisible to lookups, the
  // range is freed when the last pin drops (plasma defers free to the
  // last client Release the same way).
  kDoomed = 3,
};

struct Slot {
  uint8_t oid[kOidBytes];
  uint32_t state;
  uint32_t pins;
  uint64_t offset;  // into the data heap
  uint64_t size;
  uint64_t lru_tick;
};

// Free-list node stored inside the header's node pool (not in the data
// heap itself, so payload memory stays payload-only).
struct FreeNode {
  uint64_t offset;
  uint64_t size;
  int32_t next;  // index into node pool, -1 == end
  int32_t in_use;
};

// Per-(process, slot) pin accounting so a crashed reader's pins can be
// reclaimed (plasma reclaims a dead client's refs when its socket
// drops; the serverless arena uses pid liveness instead).
struct PinRec {
  int32_t pid;
  int32_t slot;
  uint32_t count;
  uint32_t in_use;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // data heap bytes
  uint64_t used;           // allocated bytes
  uint64_t lru_clock;
  uint32_t num_slots;
  uint32_t num_free_nodes;
  int32_t free_head;       // free-list head (node index)
  uint32_t num_pin_recs;
  uint32_t initialized;
  uint32_t _pad;
  // Pins taken while a slot's ledger bucket was full (>kPinRecsPerSlot
  // distinct live pids on one slot): safe but not crash-reclaimable.
  uint64_t untracked_pins;
  pthread_mutex_t mutex;
  // Slot table, node pool, and pin ledger follow; data heap after.
};

struct Handle {
  int fd;
  uint8_t* map;
  uint64_t map_size;
  Header* header;
  Slot* slots;
  FreeNode* nodes;
  PinRec* pins;
  uint8_t* heap;
};

uint64_t AlignUp(uint64_t v, uint64_t a) { return (v + a - 1) / a * a; }

Slot* FindSlot(Handle* h, const uint8_t* oid) {
  // Linear probe from the oid's hash position. Doomed slots are
  // invisible by oid: a deleted-while-pinned object must not block
  // re-creation of the same (immutable) object id by lineage
  // reconstruction — the doomed slot is reachable only through the
  // pin ledger's slot index until its last pin drops.
  uint64_t hash = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kOidBytes; ++i) {
    hash = (hash ^ oid[i]) * 1099511628211ULL;
  }
  const uint32_t n = h->header->num_slots;
  for (uint32_t probe = 0; probe < n; ++probe) {
    Slot* slot = &h->slots[(hash + probe) % n];
    if (slot->state != kFree && slot->state != kDoomed &&
        memcmp(slot->oid, oid, kOidBytes) == 0) {
      return slot;
    }
  }
  return nullptr;
}

void DeleteSlotLocked(Handle* h, Slot* slot);

// Ledger helpers (call with the arena mutex held). Recs are bucketed:
// slot i owns indices [i*kPinRecsPerSlot, (i+1)*kPinRecsPerSlot), so
// pin/unpin touch O(kPinRecsPerSlot) entries, not the whole ledger.
PinRec* FindPinRec(Handle* h, int32_t pid, int32_t slot) {
  for (uint32_t k = 0; k < kPinRecsPerSlot; ++k) {
    PinRec* rec = &h->pins[slot * kPinRecsPerSlot + k];
    if (rec->in_use && rec->pid == pid) return rec;
  }
  return nullptr;
}

// Reclaim bucket entries owned by dead pids (without freeing the slot
// itself — callers handle doomed-slot cleanup).
void ReapBucketLocked(Handle* h, int32_t slot_index) {
  Slot* slot = &h->slots[slot_index];
  for (uint32_t k = 0; k < kPinRecsPerSlot; ++k) {
    PinRec* rec = &h->pins[slot_index * kPinRecsPerSlot + k];
    if (rec->in_use && kill(rec->pid, 0) != 0 && errno == ESRCH) {
      slot->pins =
          (slot->pins > rec->count) ? slot->pins - rec->count : 0;
      rec->in_use = 0;
    }
  }
}

PinRec* AllocPinRec(Handle* h, int32_t slot) {
  for (uint32_t k = 0; k < kPinRecsPerSlot; ++k) {
    PinRec* rec = &h->pins[slot * kPinRecsPerSlot + k];
    if (!rec->in_use) return rec;
  }
  // Bucket full: entries may belong to dead pids — reap and retry so
  // OOM-killed readers can't permanently exhaust a slot's bucket.
  ReapBucketLocked(h, slot);
  for (uint32_t k = 0; k < kPinRecsPerSlot; ++k) {
    PinRec* rec = &h->pins[slot * kPinRecsPerSlot + k];
    if (!rec->in_use) return rec;
  }
  return nullptr;
}

void FreeDoomedIfUnpinned(Handle* h, Slot* slot) {
  if (slot->state == kDoomed && slot->pins == 0) {
    DeleteSlotLocked(h, slot);
  }
}

Slot* FindEmptySlot(Handle* h, const uint8_t* oid) {
  uint64_t hash = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kOidBytes; ++i) {
    hash = (hash ^ oid[i]) * 1099511628211ULL;
  }
  const uint32_t n = h->header->num_slots;
  for (uint32_t probe = 0; probe < n; ++probe) {
    Slot* slot = &h->slots[(hash + probe) % n];
    if (slot->state == kFree) return slot;
  }
  return nullptr;
}

int32_t AllocNode(Handle* h) {
  for (uint32_t i = 0; i < h->header->num_free_nodes; ++i) {
    if (!h->nodes[i].in_use) {
      h->nodes[i].in_use = 1;
      return static_cast<int32_t>(i);
    }
  }
  return -1;
}

// First-fit allocation from the free list.
int64_t HeapAlloc(Handle* h, uint64_t size) {
  Header* hd = h->header;
  int32_t prev = -1;
  int32_t cur = hd->free_head;
  while (cur >= 0) {
    FreeNode* node = &h->nodes[cur];
    if (node->size >= size) {
      uint64_t offset = node->offset;
      if (node->size == size) {
        if (prev < 0) hd->free_head = node->next;
        else h->nodes[prev].next = node->next;
        node->in_use = 0;
      } else {
        node->offset += size;
        node->size -= size;
      }
      hd->used += size;
      return static_cast<int64_t>(offset);
    }
    prev = cur;
    cur = node->next;
  }
  return -1;
}

// Insert a free range, merging neighbors (offset-sorted list).
void HeapFree(Handle* h, uint64_t offset, uint64_t size) {
  Header* hd = h->header;
  hd->used -= size;
  int32_t prev = -1;
  int32_t cur = hd->free_head;
  while (cur >= 0 && h->nodes[cur].offset < offset) {
    prev = cur;
    cur = h->nodes[cur].next;
  }
  // Merge with previous?
  if (prev >= 0 &&
      h->nodes[prev].offset + h->nodes[prev].size == offset) {
    h->nodes[prev].size += size;
    // Merge previous with current?
    if (cur >= 0 && h->nodes[prev].offset + h->nodes[prev].size ==
                        h->nodes[cur].offset) {
      h->nodes[prev].size += h->nodes[cur].size;
      h->nodes[prev].next = h->nodes[cur].next;
      h->nodes[cur].in_use = 0;
    }
    return;
  }
  // Merge with current?
  if (cur >= 0 && offset + size == h->nodes[cur].offset) {
    h->nodes[cur].offset = offset;
    h->nodes[cur].size += size;
    return;
  }
  int32_t fresh = AllocNode(h);
  if (fresh < 0) return;  // node pool exhausted: leak range (rare)
  h->nodes[fresh].offset = offset;
  h->nodes[fresh].size = size;
  h->nodes[fresh].next = cur;
  if (prev < 0) hd->free_head = fresh;
  else h->nodes[prev].next = fresh;
}

void DeleteSlotLocked(Handle* h, Slot* slot) {
  HeapFree(h, slot->offset, AlignUp(slot->size ? slot->size : 1, kAlign));
  slot->state = kFree;
  slot->pins = 0;
}

// Evict the single LRU sealed+unpinned object; false if none exists.
bool EvictOneLocked(Handle* h, uint8_t* evicted_out, int* count,
                    int max_evicted) {
  if (*count >= max_evicted) return false;
  Header* hd = h->header;
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < hd->num_slots; ++i) {
    Slot* slot = &h->slots[i];
    if (slot->state == kSealed && slot->pins == 0 &&
        (victim == nullptr || slot->lru_tick < victim->lru_tick)) {
      victim = slot;
    }
  }
  if (victim == nullptr) return false;
  memcpy(evicted_out + *count * kOidBytes, victim->oid, kOidBytes);
  ++(*count);
  DeleteSlotLocked(h, victim);
  return true;
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->header->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock (e.g. the OOM killer SIGKILLed
      // a worker mid-create). The shared state may hold a CREATING
      // slot that will never seal — acceptable garbage — but the
      // mutex must be marked consistent or it becomes permanently
      // unusable (ENOTRECOVERABLE) for every process.
      pthread_mutex_consistent(&h_->header->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->header->mutex); }

 private:
  Handle* h_;
};

}  // namespace

extern "C" {

// Error codes.
#define RTS_OK 0
#define RTS_ERR_EXISTS -2
#define RTS_ERR_FULL -3
#define RTS_ERR_MISSING -4
#define RTS_ERR_STATE -5
#define RTS_ERR_SYS -6

void* rts_open(const char* path, uint64_t capacity, uint32_t num_slots,
               int create) {
  const uint64_t node_pool = num_slots;  // one free node per slot
  const uint64_t pin_pool = num_slots * kPinRecsPerSlot;
  const uint64_t meta_size =
      AlignUp(sizeof(Header) + num_slots * sizeof(Slot) +
                  node_pool * sizeof(FreeNode) +
                  pin_pool * sizeof(PinRec),
              kAlign);
  const uint64_t total = meta_size + capacity;
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (create) {
    if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
      close(fd);
      return nullptr;
    }
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < total) {
    close(fd);
    return nullptr;
  }
  uint8_t* map = static_cast<uint8_t*>(mmap(
      nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle;
  h->fd = fd;
  h->map = map;
  h->map_size = total;
  h->header = reinterpret_cast<Header*>(map);
  h->slots = reinterpret_cast<Slot*>(map + sizeof(Header));
  h->nodes = reinterpret_cast<FreeNode*>(
      map + sizeof(Header) + num_slots * sizeof(Slot));
  h->pins = reinterpret_cast<PinRec*>(
      map + sizeof(Header) + num_slots * sizeof(Slot) +
      node_pool * sizeof(FreeNode));
  h->heap = map + meta_size;
  if (create && h->header->initialized != 1) {
    Header* hd = h->header;
    memset(map, 0, meta_size);
    hd->magic = kMagic;
    hd->capacity = capacity;
    hd->used = 0;
    hd->lru_clock = 0;
    hd->num_slots = num_slots;
    hd->num_free_nodes = static_cast<uint32_t>(node_pool);
    hd->num_pin_recs = static_cast<uint32_t>(pin_pool);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hd->mutex, &attr);
    h->nodes[0].offset = 0;
    h->nodes[0].size = capacity;
    h->nodes[0].next = -1;
    h->nodes[0].in_use = 1;
    hd->free_head = 0;
    __sync_synchronize();
    hd->initialized = 1;
  }
  if (h->header->magic != kMagic) {
    munmap(map, total);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

uint8_t* rts_base(void* handle) {
  return static_cast<Handle*>(handle)->heap;
}

int64_t rts_create(void* handle, const uint8_t* oid, uint64_t size,
                   uint8_t* evicted_out, int max_evicted,
                   int* n_evicted) {
  Handle* h = static_cast<Handle*>(handle);
  uint64_t need = AlignUp(size ? size : 1, kAlign);
  Locker lock(h);
  *n_evicted = 0;
  if (FindSlot(h, oid) != nullptr) return RTS_ERR_EXISTS;
  if (need > h->header->capacity) return RTS_ERR_FULL;
  // Keep evicting LRU victims until a contiguous range exists —
  // byte-count checks alone miss fragmentation (freed neighbors must
  // coalesce before a large allocation fits).
  int64_t offset = HeapAlloc(h, need);
  while (offset < 0 &&
         EvictOneLocked(h, evicted_out, n_evicted, max_evicted)) {
    offset = HeapAlloc(h, need);
  }
  if (offset < 0) return RTS_ERR_FULL;
  Slot* slot = FindEmptySlot(h, oid);
  if (slot == nullptr) {
    HeapFree(h, static_cast<uint64_t>(offset), need);
    return RTS_ERR_FULL;
  }
  memcpy(slot->oid, oid, kOidBytes);
  slot->state = kCreating;
  slot->pins = 0;
  slot->offset = static_cast<uint64_t>(offset);
  slot->size = size;
  slot->lru_tick = ++h->header->lru_clock;
#ifdef MADV_POPULATE_WRITE
  // Pre-fault the extent so the producer's memcpy streams into mapped
  // pages instead of paying a page fault per 4K (plasma pre-touches
  // its arena the same way). First writes to fresh /dev/shm pages
  // otherwise dominate large-object put latency. Best-effort: EINVAL
  // on old kernels is fine.
  madvise(h->heap + offset, need, MADV_POPULATE_WRITE);
#endif
  return offset;
}

int rts_seal(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->state != kCreating) return RTS_ERR_STATE;
  slot->state = kSealed;
  return RTS_OK;
}

// Pin accounting for one slot (caller holds the arena lock): ledger
// record for crash reclaim, pin count, LRU touch, and the caller's
// view coordinates. Shared by rts_pin and rts_seal_pinned.
int64_t PinSlotLocked(Handle* h, Slot* slot, uint64_t* offset_out,
                      uint64_t* size_out) {
  int32_t index = static_cast<int32_t>(slot - h->slots);
  int32_t pid = static_cast<int32_t>(getpid());
  PinRec* rec = FindPinRec(h, pid, index);
  if (rec == nullptr) rec = AllocPinRec(h, index);
  if (rec != nullptr) {
    if (!rec->in_use) {
      rec->in_use = 1;
      rec->pid = pid;
      rec->slot = index;
      rec->count = 0;
    }
    rec->count += 1;
  } else {
    // Bucket exhaustion: still pin (reader safety beats reclaim).
    h->header->untracked_pins += 1;
  }
  slot->pins += 1;
  slot->lru_tick = ++h->header->lru_clock;
  *offset_out = slot->offset;
  *size_out = slot->size;
  return index;
}

// Seal + take a reader pin in ONE critical section. A creator that
// seals then pins in two calls leaves a window where the brand-new
// SEALED slot (pins == 0) is an LRU-eviction candidate — a concurrent
// create() in another process could destroy the only copy before the
// daemon's primary pin lands. Returns the slot index (>= 0) for
// rts_unpin_idx, with offset/size for the caller's view.
int64_t rts_seal_pinned(void* handle, const uint8_t* oid,
                        uint64_t* offset_out, uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->state != kCreating) return RTS_ERR_STATE;
  slot->state = kSealed;
  return PinSlotLocked(h, slot, offset_out, size_out);
}

// Looks up a SEALED object; returns offset, fills size. -4 if absent
// or unsealed (sealed_only=0 accepts CREATING too).
int64_t rts_lookup(void* handle, const uint8_t* oid, uint64_t* size_out,
                   int sealed_only) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (sealed_only && slot->state != kSealed) return RTS_ERR_MISSING;
  slot->lru_tick = ++h->header->lru_clock;
  *size_out = slot->size;
  return static_cast<int64_t>(slot->offset);
}

// Atomically pin the SEALED slot holding `oid` and report its
// offset/size under one critical section — the caller must build its
// view from these, never from a separate lookup, or a concurrent
// delete + re-create of the same oid could hand it an unpinned slot's
// memory (ABA). Returns the slot index (>=0) for rts_unpin_idx,
// RTS_ERR_MISSING if absent/doomed, RTS_ERR_STATE if not yet sealed.
// The ledger records (pid, slot, count) so rts_reap_dead_pins can
// reclaim pins of crashed readers.
int64_t rts_pin(void* handle, const uint8_t* oid, uint64_t* offset_out,
                uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->state != kSealed) return RTS_ERR_STATE;
  return PinSlotLocked(h, slot, offset_out, size_out);
}

int rts_unpin_idx(void* handle, int32_t index) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  if (index < 0 ||
      static_cast<uint32_t>(index) >= h->header->num_slots) {
    return RTS_ERR_MISSING;
  }
  Slot* slot = &h->slots[index];
  if (slot->state == kFree) return RTS_ERR_MISSING;
  PinRec* rec = FindPinRec(h, static_cast<int32_t>(getpid()), index);
  if (rec != nullptr) {
    rec->count -= 1;
    if (rec->count == 0) rec->in_use = 0;
  }
  if (slot->pins > 0) slot->pins -= 1;
  FreeDoomedIfUnpinned(h, slot);
  return RTS_OK;
}

// Reclaim pins held by processes that no longer exist. Returns the
// number of pins reclaimed. Intended for the node daemon's periodic
// maintenance tick (and before surfacing an arena-full error).
int rts_reap_dead_pins(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  int reclaimed = 0;
  for (uint32_t i = 0; i < h->header->num_pin_recs; ++i) {
    PinRec* rec = &h->pins[i];
    if (!rec->in_use) continue;
    if (kill(rec->pid, 0) != 0 && errno == ESRCH) {
      Slot* slot = &h->slots[i / kPinRecsPerSlot];
      uint32_t n = rec->count;
      if (slot->state != kFree) {
        slot->pins = (slot->pins > n) ? slot->pins - n : 0;
        FreeDoomedIfUnpinned(h, slot);
      }
      reclaimed += static_cast<int>(n);
      rec->in_use = 0;
    }
  }
  return reclaimed;
}

uint64_t rts_untracked_pins(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  return h->header->untracked_pins;
}

int rts_delete(void* handle, const uint8_t* oid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* slot = FindSlot(h, oid);
  if (slot == nullptr) return RTS_ERR_MISSING;
  if (slot->pins > 0) {
    // Readers still mapped: defer the free to the last unpin so their
    // zero-copy views stay valid (delete-while-mapped safety). The
    // doomed slot is invisible to FindSlot, so the oid can be
    // re-created immediately.
    slot->state = kDoomed;
    return RTS_OK;
  }
  DeleteSlotLocked(h, slot);
  return RTS_OK;
}

int rts_stats(void* handle, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  *capacity = h->header->capacity;
  *used = h->header->used;
  uint64_t count = 0;
  for (uint32_t i = 0; i < h->header->num_slots; ++i) {
    if (h->slots[i].state != kFree) ++count;
  }
  *num_objects = count;
  return RTS_OK;
}

void rts_close(void* handle, int unlink_file, const char* path) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->map, h->map_size);
  close(h->fd);
  if (unlink_file && path != nullptr) unlink(path);
  delete h;
}

// Cross-process atomic accessors for shared-memory ring buffers
// (dag/channels.py): acquire/release orderings make the
// payload-then-counter publication pattern correct on any
// architecture, not just x86-TSO.
uint64_t rts_load_acq_u64(const void* p) {
  return __atomic_load_n(static_cast<const uint64_t*>(p),
                         __ATOMIC_ACQUIRE);
}

void rts_store_rel_u64(void* p, uint64_t v) {
  __atomic_store_n(static_cast<uint64_t*>(p), v, __ATOMIC_RELEASE);
}

// Futex doorbell for the SPSC channel counters (dag/channels.py).
// The waiter sleeps in the kernel on the LOW 32 bits of a u64
// head/tail counter (little-endian: the low word changes on every
// advance) instead of sleep-polling; the peer rings after each
// counter store. Non-PRIVATE futexes are required — the two sides
// are different processes mapping the same segment (reference
// semantics: mutable-object WaitForWritten/WaitForReadable,
// core_worker/experimental_mutable_object_manager.h:48,153).
int rts_futex_wait_u32(void* p, uint32_t expected, int64_t timeout_ns) {
  struct timespec ts;
  struct timespec* tsp = nullptr;
  if (timeout_ns >= 0) {
    ts.tv_sec = timeout_ns / 1000000000;
    ts.tv_nsec = timeout_ns % 1000000000;
    tsp = &ts;
  }
  long rc = syscall(SYS_futex, p, FUTEX_WAIT, expected, tsp, nullptr, 0);
  return rc == 0 ? 0 : -errno;
}

int rts_futex_wake(void* p, int n) {
  long rc = syscall(SYS_futex, p, FUTEX_WAKE, n, nullptr, nullptr, 0);
  return rc >= 0 ? static_cast<int>(rc) : -errno;
}

// ---------------------------------------------------------------------------
// Whole-operation SPSC ring put/get (dag/channels.py hot path).
//
// Same segment layout as the Python implementation (three u64s —
// head/tail/closed — then `capacity` data bytes), so the two
// implementations interoperate and pure Python remains the fallback
// when the toolchain is absent. Collapsing one put or get into a
// single FFI call matters because the Python path pays ~6 ctypes
// round-trips + interpreter bytecode per hop: measured 39us/hop
// two-process ping-pong vs a 6.9us OS-pipe floor on the 1-core CI
// box; this path closes most of that gap (MICROBENCH dag_hop_per_s).
//
// Returns: 0 / payload size on success; -EPIPE closed; -ETIMEDOUT
// deadline passed; -EMSGSIZE record exceeds capacity; -E2BIG caller
// buffer too small (cannot happen when out_cap >= capacity).
// ---------------------------------------------------------------------------

namespace {

constexpr uint64_t kChanHeader = 24;
// Bounded kernel waits so a peer that died WITHOUT setting the closed
// flag (SIGKILL) is noticed by the next deadline check instead of
// sleeping forever; close() rings the futex so the common case wakes
// immediately.
constexpr int64_t kChanWaitChunkNs = 200 * 1000 * 1000;

inline int64_t mono_now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000000 + ts.tv_nsec;
}

inline void ring_copy_in(uint8_t* data, uint64_t cap, uint64_t pos,
                         const uint8_t* src, uint64_t n) {
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  memcpy(data + off, src, first);
  if (first < n) memcpy(data, src + first, n - first);
}

inline void ring_copy_out(const uint8_t* data, uint64_t cap, uint64_t pos,
                          uint8_t* dst, uint64_t n) {
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  memcpy(dst, data + off, first);
  if (first < n) memcpy(dst + first, data, n - first);
}

// Wait for the low u32 of the counter at `watch` to leave `snap`;
// honors an absolute deadline (deadline_ns < 0 = infinite).
inline int chan_wait(uint64_t* watch, uint32_t snap, int64_t deadline_ns) {
  int64_t chunk = kChanWaitChunkNs;
  if (deadline_ns >= 0) {
    int64_t left = deadline_ns - mono_now_ns();
    if (left <= 0) return -ETIMEDOUT;
    if (left < chunk) chunk = left;
  }
  struct timespec ts;
  ts.tv_sec = chunk / 1000000000;
  ts.tv_nsec = chunk % 1000000000;
  syscall(SYS_futex, watch, FUTEX_WAIT, snap, &ts, nullptr, 0);
  return 0;  // EAGAIN/EINTR/timeout chunks all just re-run the loop
}

}  // namespace

int rts_chan_put(void* base, uint64_t cap, const void* payload,
                 uint64_t len, int64_t timeout_ns) {
  uint8_t* b = static_cast<uint8_t*>(base);
  uint64_t* H = reinterpret_cast<uint64_t*>(b);
  uint64_t* T = reinterpret_cast<uint64_t*>(b + 8);
  uint64_t* C = reinterpret_cast<uint64_t*>(b + 16);
  uint8_t* data = b + kChanHeader;
  uint64_t record = len + 8;
  if (record > cap) return -EMSGSIZE;
  int64_t deadline = timeout_ns < 0 ? -1 : mono_now_ns() + timeout_ns;
  for (;;) {
    if (__atomic_load_n(C, __ATOMIC_ACQUIRE)) return -EPIPE;
    uint64_t head = __atomic_load_n(H, __ATOMIC_RELAXED);  // sole writer
    uint64_t tail = __atomic_load_n(T, __ATOMIC_ACQUIRE);
    if (cap - (head - tail) >= record) {
      ring_copy_in(data, cap, head, reinterpret_cast<uint8_t*>(&len), 8);
      ring_copy_in(data, cap, head + 8,
                   static_cast<const uint8_t*>(payload), len);
      __atomic_store_n(H, head + record, __ATOMIC_RELEASE);
      syscall(SYS_futex, H, FUTEX_WAKE, INT32_MAX, nullptr, nullptr, 0);
      return 0;
    }
    int rc = chan_wait(T, static_cast<uint32_t>(tail), deadline);
    if (rc != 0) return rc;
  }
}

int64_t rts_chan_get(void* base, uint64_t cap, void* out,
                     uint64_t out_cap, int64_t timeout_ns) {
  uint8_t* b = static_cast<uint8_t*>(base);
  uint64_t* H = reinterpret_cast<uint64_t*>(b);
  uint64_t* T = reinterpret_cast<uint64_t*>(b + 8);
  uint64_t* C = reinterpret_cast<uint64_t*>(b + 16);
  uint8_t* data = b + kChanHeader;
  int64_t deadline = timeout_ns < 0 ? -1 : mono_now_ns() + timeout_ns;
  for (;;) {
    uint64_t head = __atomic_load_n(H, __ATOMIC_ACQUIRE);
    uint64_t tail = __atomic_load_n(T, __ATOMIC_RELAXED);  // sole reader
    if (head - tail >= 8) {
      uint64_t size;
      ring_copy_out(data, cap, tail, reinterpret_cast<uint8_t*>(&size), 8);
      if (size > out_cap) return -E2BIG;
      ring_copy_out(data, cap, tail + 8, static_cast<uint8_t*>(out),
                    size);
      __atomic_store_n(T, tail + 8 + size, __ATOMIC_RELEASE);
      syscall(SYS_futex, T, FUTEX_WAKE, INT32_MAX, nullptr, nullptr, 0);
      return static_cast<int64_t>(size);
    }
    // Drain-before-close: records buffered ahead of a remote close()
    // are still delivered (matches the Python path's check order).
    if (__atomic_load_n(C, __ATOMIC_ACQUIRE)) return -EPIPE;
    int rc = chan_wait(H, static_cast<uint32_t>(head), deadline);
    if (rc != 0) return rc;
  }
}

}  // extern "C"
