"""Durable workflows (reference: python/ray/workflow).

workflow.run(dag) executes a task DAG with every step's result
persisted to storage before the workflow advances (reference:
workflow/api.py:123 run, workflow_executor.py:32, workflow_storage.py).
A crashed or failed workflow resumes from storage: finished steps are
loaded, only missing/failed steps re-execute. Step identity is the
node's position in the deterministic topological order plus its
function name — stable across resubmissions of the same DAG shape.

Scope note: static DAG workflows + per-step retries + resume are
implemented; dynamic continuations (steps returning new DAGs) and
virtual actors are out of scope this round and documented as gaps.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode, InputNode

_DEFAULT_ROOT = os.path.join(
    tempfile.gettempdir(), "rt_workflows"
)

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"


def _root(storage: Optional[str]) -> str:
    root = storage or os.environ.get("RT_WORKFLOW_STORAGE", _DEFAULT_ROOT)
    os.makedirs(root, exist_ok=True)
    return root


class _WorkflowStorage:
    """(reference: workflow/workflow_storage.py — step results +
    workflow metadata under a per-workflow prefix)."""

    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(root, workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self._meta_path = os.path.join(self.dir, "meta.json")

    def save_meta(self, meta: dict) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path)

    def load_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"step-{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_dag(self, dag: DAGNode, input_value: Any) -> None:
        import cloudpickle

        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump({"dag": dag, "input": input_value}, f)

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            state = pickle.load(f)
        return state["dag"], state["input"]


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic ids keyed by node identity."""
    ids: Dict[int, str] = {}
    for index, node in enumerate(dag.topological_order()):
        if isinstance(node, FunctionNode):
            name = node._rf.underlying.__name__
        else:
            name = type(node).__name__.lower()
        ids[id(node)] = f"{index:03d}-{name}"
    return ids


def _execute(
    dag: DAGNode,
    input_value: Any,
    storage: _WorkflowStorage,
) -> Any:
    """Walk the DAG; each step's result is durable before dependents
    run (reference: workflow_executor commit-before-advance)."""
    import ray_tpu as rt

    ids = _step_ids(dag)
    cache: Dict[int, Any] = {}
    for node in dag.topological_order():
        step_id = ids[id(node)]
        if isinstance(node, InputNode):
            cache[id(node)] = input_value
            continue
        if storage.has_step(step_id):
            cache[id(node)] = storage.load_step(step_id)
            continue
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflows support task nodes only, got "
                f"{type(node).__name__}"
            )
        args = [
            cache[id(a)] if isinstance(a, DAGNode) else a
            for a in node._bound_args
        ]
        kwargs = {
            k: cache[id(v)] if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()
        }
        ref = node._rf.remote(*args, **kwargs)
        value = rt.get(ref, timeout=600)
        storage.save_step(step_id, value)
        cache[id(node)] = value
    return cache[id(dag)]


def run(
    dag: DAGNode,
    *,
    workflow_id: Optional[str] = None,
    input_value: Any = None,
    storage: Optional[str] = None,
) -> Any:
    """Execute (or continue) a workflow to completion and return the
    final result (reference: workflow.run, api.py:123)."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    store = _WorkflowStorage(_root(storage), workflow_id)
    store.save_dag(dag, input_value)
    store.save_meta(
        {"workflow_id": workflow_id, "status": STATUS_RUNNING}
    )
    try:
        result = _execute(dag, input_value, store)
    except BaseException as e:
        store.save_meta(
            {
                "workflow_id": workflow_id,
                "status": STATUS_FAILED,
                "error": repr(e),
            }
        )
        raise
    store.save_step("__output__", result)
    store.save_meta(
        {"workflow_id": workflow_id, "status": STATUS_SUCCESSFUL}
    )
    return result


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-drive an interrupted/failed workflow; completed steps load
    from storage (reference: workflow.resume)."""
    store = _WorkflowStorage(_root(storage), workflow_id)
    meta = store.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] == STATUS_SUCCESSFUL:
        return store.load_step("__output__")
    dag, input_value = store.load_dag()
    return run(
        dag,
        workflow_id=workflow_id,
        input_value=input_value,
        storage=storage,
    )


def get_status(
    workflow_id: str, *, storage: Optional[str] = None
) -> Optional[str]:
    meta = _WorkflowStorage(_root(storage), workflow_id).load_meta()
    return meta["status"] if meta else None


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    store = _WorkflowStorage(_root(storage), workflow_id)
    meta = store.load_meta()
    if meta is None or meta["status"] != STATUS_SUCCESSFUL:
        raise ValueError(
            f"workflow {workflow_id!r} has no output "
            f"(status={meta and meta['status']})"
        )
    return store.load_step("__output__")


def list_all(*, storage: Optional[str] = None) -> List[dict]:
    root = _root(storage)
    out = []
    for entry in sorted(os.listdir(root)):
        meta = _WorkflowStorage(root, entry).load_meta()
        if meta:
            out.append(meta)
    return out


__all__ = [
    "run",
    "resume",
    "get_status",
    "get_output",
    "list_all",
    "STATUS_RUNNING",
    "STATUS_SUCCESSFUL",
    "STATUS_FAILED",
]
