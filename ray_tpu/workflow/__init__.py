"""Durable workflows (reference: python/ray/workflow).

workflow.run(dag) executes a task DAG with every step's result
persisted to storage before the workflow advances (reference:
workflow/api.py:123 run, workflow_executor.py:32, workflow_storage.py).
A crashed or failed workflow resumes from storage: finished steps are
loaded, only missing/failed steps re-execute. Step identity is the
node's position in the deterministic topological order plus its
function name — stable across resubmissions of the same DAG shape.

Dynamic continuations (reference: workflow/api.py continuation — a
step returns `workflow.continuation(sub_dag)` and the workflow keeps
executing the returned DAG durably, sub-steps namespaced under the
parent step) and durable virtual actors (reference:
workflow/virtual_actor semantics: per-actor persistent state, each
method call a durable step) are implemented on the same storage: see
`continuation` below and `workflow.virtual_actor`.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

from ..dag.dag_node import (
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
)

_DEFAULT_ROOT = os.path.join(
    tempfile.gettempdir(), "rt_workflows"
)

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"


def _root(storage: Optional[str]) -> str:
    root = storage or os.environ.get("RT_WORKFLOW_STORAGE", _DEFAULT_ROOT)
    os.makedirs(root, exist_ok=True)
    return root


class _WorkflowStorage:
    """(reference: workflow/workflow_storage.py — step results +
    workflow metadata under a per-workflow prefix)."""

    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(root, workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self._meta_path = os.path.join(self.dir, "meta.json")

    def save_meta(self, meta: dict) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path)

    def load_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    @staticmethod
    def _fs_name(step_id: str) -> str:
        """Deep continuation prefixes grow linearly with depth; past
        the filename limit, collapse deterministically to a digest +
        readable tail (same step_id -> same file across resumes)."""
        if len(step_id) <= 150:
            return step_id
        import hashlib

        digest = hashlib.sha1(step_id.encode()).hexdigest()[:16]
        return f"{digest}-{step_id[-60:]}"

    def step_path(self, step_id: str) -> str:
        return os.path.join(
            self.dir, f"step-{self._fs_name(step_id)}.pkl"
        )

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)

    def cont_path(self, step_id: str) -> str:
        return os.path.join(
            self.dir, f"cont-{self._fs_name(step_id)}.pkl"
        )

    def has_continuation(self, step_id: str) -> bool:
        return os.path.exists(self.cont_path(step_id))

    def save_continuation(
        self, step_id: str, dag: DAGNode, input_value: Any
    ) -> None:
        import cloudpickle

        tmp = self.cont_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump({"dag": dag, "input": input_value}, f)
        os.replace(tmp, self.cont_path(step_id))

    def load_continuation(self, step_id: str):
        with open(self.cont_path(step_id), "rb") as f:
            state = pickle.load(f)
        return state["dag"], state["input"]

    def save_dag(self, dag: DAGNode, input_value: Any) -> None:
        import cloudpickle

        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump({"dag": dag, "input": input_value}, f)

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            state = pickle.load(f)
        return state["dag"], state["input"]


class Continuation:
    """A step's request to keep the workflow going with a new DAG
    (reference: ray.workflow.continuation — the dynamic-workflow
    primitive: recursion/loops whose every iteration is durable)."""

    def __init__(self, dag: DAGNode, input_value: Any = None):
        if not isinstance(dag, DAGNode):
            raise TypeError(
                f"continuation() takes a DAG node, got "
                f"{type(dag).__name__}"
            )
        self.dag = dag
        self.input_value = input_value


def continuation(dag: DAGNode, input_value: Any = None) -> Continuation:
    """Return this from a workflow step to splice `dag` in as the
    step's durable continuation; the step's final value becomes the
    continuation DAG's final value."""
    return Continuation(dag, input_value)


def _step_ids(dag: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic ids keyed by node identity."""
    ids: Dict[int, str] = {}
    for index, node in enumerate(dag.topological_order()):
        if isinstance(node, FunctionNode):
            name = node._rf.underlying.__name__
        else:
            name = type(node).__name__.lower()
        ids[id(node)] = f"{prefix}{index:03d}-{name}"
    return ids


#: Continuation depth guard: each level is durable AND the walk below
#: is an iterative trampoline (no Python recursion — a recursive
#: implementation would hit the interpreter's ~1000-frame limit around
#: depth ~300 and, worse, crash identically on every resume). The
#: guard only stops runaway non-terminating loops.
_MAX_CONTINUATION_DEPTH = 10_000


class _Frame:
    """One DAG being walked; continuations push child frames."""

    __slots__ = ("dag", "order", "ids", "cache", "input_value", "idx")

    def __init__(self, dag: DAGNode, input_value: Any, prefix: str):
        self.dag = dag
        self.order = list(dag.topological_order())
        self.ids = _step_ids(dag, prefix)
        self.cache: Dict[int, Any] = {}
        self.input_value = input_value
        self.idx = 0


def _execute(
    dag: DAGNode,
    input_value: Any,
    storage: _WorkflowStorage,
) -> Any:
    """Walk the DAG; each step's result is durable before dependents
    run (reference: workflow_executor commit-before-advance). A step
    returning a Continuation pushes a child frame: the sub-DAG is
    persisted first (so resume never re-runs the generating step) and
    executed with sub-steps namespaced under the parent id."""
    import ray_tpu as rt

    stack = [_Frame(dag, input_value, "")]

    def push(sub_dag, sub_input, parent_step_id):
        if len(stack) >= _MAX_CONTINUATION_DEPTH:
            raise RecursionError(
                f"workflow continuation depth exceeded "
                f"{_MAX_CONTINUATION_DEPTH}"
            )
        stack.append(
            _Frame(sub_dag, sub_input, f"{parent_step_id}.")
        )

    while True:
        frame = stack[-1]
        if frame.idx >= len(frame.order):
            # Frame done: its dag's value either IS the workflow
            # output or resolves the parent's pending continuation.
            result = frame.cache[id(frame.dag)]
            stack.pop()
            if not stack:
                return result
            parent = stack[-1]
            node = parent.order[parent.idx]
            step_id = parent.ids[id(node)]
            storage.save_step(step_id, result)
            parent.cache[id(node)] = result
            parent.idx += 1
            continue
        node = frame.order[frame.idx]
        step_id = frame.ids[id(node)]
        if isinstance(node, InputNode):
            frame.cache[id(node)] = frame.input_value
            frame.idx += 1
            continue
        if isinstance(node, InputAttributeNode):
            # inp["key"] projection — the InputNode child resolved in
            # an earlier topological slot.
            frame.cache[id(node)] = frame.cache[id(node.input_node)][
                node.key
            ]
            frame.idx += 1
            continue
        if storage.has_step(step_id):
            frame.cache[id(node)] = storage.load_step(step_id)
            frame.idx += 1
            continue
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflows support task nodes only, got "
                f"{type(node).__name__}"
            )
        if storage.has_continuation(step_id):
            # Crashed mid-continuation: resume the sub-DAG without
            # re-running the (already committed) generating step.
            sub_dag, sub_input = storage.load_continuation(step_id)
            push(sub_dag, sub_input, step_id)
            continue
        args = [
            frame.cache[id(a)] if isinstance(a, DAGNode) else a
            for a in node._bound_args
        ]
        kwargs = {
            k: frame.cache[id(v)] if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()
        }
        ref = node._rf.remote(*args, **kwargs)
        value = rt.get(ref, timeout=600)
        if isinstance(value, Continuation):
            # Durable before running: resume re-enters the sub-DAG.
            storage.save_continuation(
                step_id, value.dag, value.input_value
            )
            push(value.dag, value.input_value, step_id)
            continue
        storage.save_step(step_id, value)
        frame.cache[id(node)] = value
        frame.idx += 1


def run(
    dag: DAGNode,
    *,
    workflow_id: Optional[str] = None,
    input_value: Any = None,
    storage: Optional[str] = None,
) -> Any:
    """Execute (or continue) a workflow to completion and return the
    final result (reference: workflow.run, api.py:123)."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"  # rt: noqa[RT003] — id minted once at submission, never replayed
    store = _WorkflowStorage(_root(storage), workflow_id)
    store.save_dag(dag, input_value)
    store.save_meta(
        {"workflow_id": workflow_id, "status": STATUS_RUNNING}
    )
    try:
        result = _execute(dag, input_value, store)
    except BaseException as e:
        store.save_meta(
            {
                "workflow_id": workflow_id,
                "status": STATUS_FAILED,
                "error": repr(e),
            }
        )
        raise
    store.save_step("__output__", result)
    store.save_meta(
        {"workflow_id": workflow_id, "status": STATUS_SUCCESSFUL}
    )
    return result


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-drive an interrupted/failed workflow; completed steps load
    from storage (reference: workflow.resume)."""
    store = _WorkflowStorage(_root(storage), workflow_id)
    meta = store.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] == STATUS_SUCCESSFUL:
        return store.load_step("__output__")
    dag, input_value = store.load_dag()
    return run(
        dag,
        workflow_id=workflow_id,
        input_value=input_value,
        storage=storage,
    )


def get_status(
    workflow_id: str, *, storage: Optional[str] = None
) -> Optional[str]:
    meta = _WorkflowStorage(_root(storage), workflow_id).load_meta()
    return meta["status"] if meta else None


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    store = _WorkflowStorage(_root(storage), workflow_id)
    meta = store.load_meta()
    if meta is None or meta["status"] != STATUS_SUCCESSFUL:
        raise ValueError(
            f"workflow {workflow_id!r} has no output "
            f"(status={meta and meta['status']})"
        )
    return store.load_step("__output__")


def list_all(*, storage: Optional[str] = None) -> List[dict]:
    root = _root(storage)
    out = []
    for entry in sorted(os.listdir(root)):
        meta = _WorkflowStorage(root, entry).load_meta()
        if meta:
            out.append(meta)
    return out


from .virtual_actor import (  # noqa: E402
    VirtualActorClass,
    get_actor,
    readonly as virtual_actor_readonly,
    virtual_actor,
)

__all__ = [
    "run",
    "resume",
    "continuation",
    "Continuation",
    "virtual_actor",
    "virtual_actor_readonly",
    "get_actor",
    "VirtualActorClass",
    "get_status",
    "get_output",
    "list_all",
    "STATUS_RUNNING",
    "STATUS_SUCCESSFUL",
    "STATUS_FAILED",
]
