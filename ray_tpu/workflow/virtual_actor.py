"""Durable virtual actors.

Reference: Ray workflow's virtual actors — an actor whose state lives
in workflow storage rather than process memory: `get_or_create`
materializes it anywhere, every method call is a durable step (state
persisted with the return value before the call "happened"), and a
crashed host loses nothing past the last completed call.

TPU-native framing: state is pickled to the workflow store under the
actor id; each call appends a numbered step record
(`call-<n>-<method>`) holding (state_after, return_value) atomically
in one file, and the state snapshot advances only together with its
call record — a crash between the two re-runs at most the one
uncommitted call. Methods marked `@readonly` skip the commit
entirely.

The method body executes as a task on the cluster (so heavy state
transitions can run on any node); the actor object itself is just a
client handle over storage.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import pickle
import re
from typing import Any, Dict, Optional

from . import _WorkflowStorage, _root


def readonly(method):
    """Mark a virtual-actor method as not mutating state: the call
    runs against the latest snapshot and commits nothing."""
    method.__rt_workflow_readonly__ = True
    return method


class _VirtualActorHandle:
    def __init__(self, cls, actor_id: str, storage_root: str):
        self._cls = cls
        self._actor_id = actor_id
        self._store = _WorkflowStorage(
            storage_root, f"va-{actor_id}"
        )

    # -- durable state ------------------------------------------------
    @contextlib.contextmanager
    def _exclusive(self):
        """Per-actor advisory lock (POSIX flock on a lockfile in the
        actor's storage dir). Serializes the read-state -> run ->
        commit window across handles and processes so two concurrent
        calls can't compute the same call number and overwrite each
        other's committed record. Scope: hosts sharing the storage
        path via a lock-honoring filesystem (local disk, most NFSv4)."""
        lock_path = os.path.join(self._store.dir, ".lock")
        with open(lock_path, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)

    def _state_path(self) -> str:
        return os.path.join(self._store.dir, "state.pkl")

    def _load_state(self):
        """Current state = the latest committed call's state-after;
        the init snapshot only seeds an actor with no calls yet. One
        atomic file per call means there is no window where a call's
        result is visible without its state change."""
        latest_n, latest_id = -1, None
        for fname in os.listdir(self._store.dir):
            m = re.match(r"step-(call-(\d+)-\w+)\.pkl$", fname)
            if m and int(m.group(2)) > latest_n:
                latest_n, latest_id = int(m.group(2)), m.group(1)
        if latest_id is not None:
            return self._store.load_step(latest_id)["state"]
        with open(self._state_path(), "rb") as f:
            return pickle.load(f)

    def _save_state(self, state) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._state_path())

    def _next_call_number(self) -> int:
        numbers = [
            int(m.group(1))
            for name in os.listdir(self._store.dir)
            if (m := re.match(r"step-call-(\d+)-", name))
        ]
        return max(numbers, default=-1) + 1

    # -- calls --------------------------------------------------------
    def _call(self, method_name: str, args, kwargs) -> Any:
        import ray_tpu as rt

        method = getattr(self._cls, method_name)
        is_readonly = getattr(
            method, "__rt_workflow_readonly__", False
        )
        cls = self._cls

        def _run_method(state_dict, m_args, m_kwargs):
            obj = cls.__new__(cls)
            obj.__dict__.update(state_dict)
            result = getattr(obj, method_name)(*m_args, **m_kwargs)
            return obj.__dict__, result

        runner = rt.remote(_run_method)

        if is_readonly:
            state = self._load_state()
            _, result = rt.get(
                runner.remote(state, list(args), dict(kwargs)),
                timeout=600,
            )
            return result

        # Mutating calls hold the actor lock across the whole
        # read -> run -> commit window: concurrent handles serialize,
        # each sees the previous call's state, and call numbers can't
        # collide/overwrite.
        with self._exclusive():
            state = self._load_state()
            new_state, result = rt.get(
                runner.remote(state, list(args), dict(kwargs)),
                timeout=600,
            )
            call_id = (
                f"call-{self._next_call_number():06d}-{method_name}"
            )
            # One atomic commit: state_after + return value.
            self._store.save_step(
                call_id, {"state": new_state, "result": result}
            )
        return result

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if not callable(getattr(self._cls, name, None)):
            raise AttributeError(
                f"{self._cls.__name__} has no method {name!r}"
            )

        class _Method:
            def __init__(self, handle):
                self._handle = handle

            def run(self, *args, **kwargs):
                return self._handle._call(name, args, kwargs)

        return _Method(self)

    # -- introspection ------------------------------------------------
    def call_log(self) -> list:
        """Committed calls, in order: [{call, method, result}]."""
        entries = []
        for fname in sorted(os.listdir(self._store.dir)):
            m = re.match(r"step-(call-(\d+)-(\w+))\.pkl$", fname)
            if not m:
                continue
            record = self._store.load_step(m.group(1))
            entries.append(
                {
                    "call": int(m.group(2)),
                    "method": m.group(3),
                    "result": record["result"],
                }
            )
        return entries


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(
        self,
        actor_id: str,
        *init_args,
        storage: Optional[str] = None,
        **init_kwargs,
    ) -> _VirtualActorHandle:
        root = _root(storage)
        handle = _VirtualActorHandle(self._cls, actor_id, root)
        with handle._exclusive():
            if not os.path.exists(handle._state_path()):
                obj = self._cls(*init_args, **init_kwargs)
                handle._save_state(dict(obj.__dict__))
                handle._store.save_meta(
                    {
                        "workflow_id": f"va-{actor_id}",
                        "status": "VIRTUAL_ACTOR",
                        "class": self._cls.__name__,
                    }
                )
        return handle


#: Registry so get_actor can resolve classes by name within a process.
_CLASSES: Dict[str, VirtualActorClass] = {}


def virtual_actor(cls) -> VirtualActorClass:
    """Class decorator: `@workflow.virtual_actor`."""
    wrapped = VirtualActorClass(cls)
    _CLASSES[cls.__name__] = wrapped
    return wrapped


def get_actor(
    actor_id: str, *, storage: Optional[str] = None
) -> _VirtualActorHandle:
    """Reattach to an existing virtual actor by id (reference:
    workflow.get_actor). The class must be imported (decorated) in
    this process."""
    root = _root(storage)
    store = _WorkflowStorage(root, f"va-{actor_id}")
    meta = store.load_meta()
    if meta is None:
        raise ValueError(f"no virtual actor {actor_id!r}")
    wrapped = _CLASSES.get(meta.get("class", ""))
    if wrapped is None:
        raise ValueError(
            f"virtual actor class {meta.get('class')!r} not "
            f"registered in this process"
        )
    return _VirtualActorHandle(wrapped._cls, actor_id, root)
