"""Continuous-batching LLM inference engine over a PAGED KV cache.

PR 10 built the batching loop on fixed slot arenas; this engine keeps
the loop and swaps the memory system (ISSUE 11 tentpole): requests
now hold refcounted `block_len`-sized pages of ONE shared pool
(kv_slots.PagedKVCache) instead of each reserving a max_len arena
row, so long-context and short-chat requests share memory, and a
request whose prompt prefix is already pooled (same system prompt)
SKIPS prefill for the covered blocks entirely. The background step
loop, every iteration:

  1. reaps cancellations and frees their slots + blocks immediately;
  2. admits the FIFO head of the waiting queue — gated on KV-block
     availability (not enough blocks: the head WAITS, no skip-ahead,
     no crash) — pinning any prefix-cache hit and reserving the rest
     of its pages, then advances its prefill by ONE fixed-size chunk
     written straight into its pages (Sarathi-style interleave, now
     starting AFTER the shared prefix);
  3. runs ONE jitted paged decode step over the FULL slot batch
     (static shapes: full-width block tables, dead rows masked and
     parked on the null block) — `models/generate.paged_decode_step`,
     buffer-donated on accelerator backends — streaming each live
     row's token to its consumer queue;
  4. retires EOS/budget rows, releasing slots and unpinning blocks in
     the same iteration (full prompt blocks stay cached for future
     prefix hits until memory pressure evicts them).

Requests are host-side objects; per-request device state is the pages
its table points at + one row of `last_logits`. Sampling parameters
stay engine-level statics (jit statics in the shared kernel; greedy
is the serving default).

Threading: submit()/cancel() may be called from any thread; all
scheduler/allocator/request state is guarded by one lock, JAX work
runs outside it. One engine = one step thread = one model family.

Failure: if the step loop dies, every in-flight and queued request is
failed with the loop's exception (consumers raise, never hang) and
subsequent submits raise EngineDead.

ISSUE 13 additions — the engine as the inference half of a decoupled
RL dataflow:

* **Drainless versioned weight sync** (`update_weights`): a weight
  push installs a new parameter GENERATION without stopping the step
  loop. Every request pins the generation that was latest at its
  ADMISSION and decodes on it to completion — a push mid-decode
  leaves in-flight streams token-exact on the old weights — while the
  next admission (and every policy batch) uses the new generation.
  During the transient mixed window the decode batch partitions by
  generation and runs one masked decode step per generation (disjoint
  alive masks over the same pool; `last_logits` rows merge back), so
  nothing is drained, shed or errored on account of the push. Old
  generations are dropped the moment their last pinned request
  retires.
* **Pluggable batch program** (`program=`, `submit_policy`): ragged
  per-env action requests are the same problem as ragged chat traffic,
  so the same step loop serves them — callers submit small row
  batches of observations from any thread, the loop coalesces
  everything pending into one padded bucket and runs the program's
  jitted forward ONCE (batched logits/action outputs), then scatters
  the rows back to their tickets. A policy-only engine passes
  ``cfg=None`` and skips the KV cache/slot machinery entirely; an LLM
  engine may serve both paths (the RLHF shape: rollout generation and
  scoring on one engine).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .kv_slots import NULL_BLOCK, PagedKVCache, default_block_len
from .scheduler import EngineDead, EngineOverloaded, SlotScheduler

__all__ = [
    "EngineConfig",
    "InferenceEngine",
    "TokenStream",
    "PolicyTicket",
    "BatchProgram",
    "EngineOverloaded",
    "EngineDead",
]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine admission/cache knobs (README "Paged KV & prefix
    caching")."""

    #: Decode-batch width = max concurrently-decoding sequences. With
    #: the paged cache this is DECOUPLED from KV memory: extra slots
    #: cost one block-table row + one logits row, not max_len of KV.
    slots: int = 4
    #: Per-REQUEST KV cap; prompt_len + max_new_tokens must fit. No
    #: longer a per-slot memory reservation — just the admission bound
    #: and the logical block-table width.
    max_len: int = 256
    #: Prefill chunk length. Prompts pad up to a multiple of this
    #: (the length-bucket set), and long prompts prefill chunk-by-
    #: chunk interleaved with decode steps.
    prefill_chunk: int = 32
    #: KV block (page) length in tokens; 0 = auto (largest divisor of
    #: prefill_chunk up to 16). Must divide prefill_chunk and max_len.
    kv_block_len: int = 0
    #: Physical KV pool size in blocks (one extra is reserved as the
    #: null block); 0 = auto: slots x max_len worth — the same memory
    #: the PR 10 arenas held, now shared on demand.
    kv_blocks: int = 0
    #: Prefix caching: full prompt blocks register under their exact
    #: token prefix; a later request with the same prefix pins the
    #: blocks and skips prefill for them. Kill switch (also
    #: RT_serve_prefix_cache_enabled via build_llm_app).
    prefix_cache: bool = True
    #: Waiting-queue bound; past it submit() raises EngineOverloaded.
    #: Size it so worst-case queue wait stays under the serve layer's
    #: 60 s per-chunk stream timeout (≈ max_waiting x max_new_tokens
    #: / batched-tokens-per-s) — a deeper queue just converts shed-
    #: fast errors into slow client timeouts that waste a slot.
    max_waiting: int = 64
    #: Default per-request token budget (requests may pass their own).
    max_new_tokens: int = 64
    #: Engine-level sampling statics (0.0 = greedy).
    temperature: float = 0.0
    top_k: int = 0
    #: Default EOS token id (-1 = none); requests may override.
    eos_token: int = -1
    #: RNG seed for sampled decoding (ignored when greedy).
    seed: int = 0
    #: Idle-loop park time waiting for work.
    idle_wait_s: float = 0.02
    #: Bound on pending policy-path rows (submit_policy sheds with
    #: EngineOverloaded past it); only meaningful with a `program`.
    max_policy_rows: int = 4096


class _Request:
    __slots__ = (
        "request_id", "prompt", "max_new_tokens", "eos_token",
        "out", "cancelled", "submitted_ts", "first_token_ts",
        "emitted", "slot", "bucket", "offset", "padded",
        "prefix_keys", "total_blocks", "block_ids", "n_shared",
        "skip", "gen",
    )

    def __init__(
        self,
        request_id: str,
        prompt: List[int],
        max_new_tokens: int,
        eos_token: int,
    ):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token = eos_token
        #: Consumer stream: ("tok", id) | ("end", reason) |
        #: ("err", exc). Unbounded — the engine must never block on a
        #: slow consumer (that would head-of-line block the whole
        #: decode batch); depth is bounded in practice by max_new.
        self.out: "queue.Queue" = queue.Queue()
        self.cancelled = threading.Event()
        self.submitted_ts = time.perf_counter()
        self.first_token_ts: Optional[float] = None
        self.emitted = 0
        # prefill progress (engine thread only)
        self.slot: Optional[int] = None
        self.bucket = 0
        self.offset = 0
        self.padded = None
        # paged-cache bookkeeping
        self.prefix_keys: List[tuple] = []
        self.total_blocks = 0
        self.block_ids: List[int] = []
        self.n_shared = 0
        self.skip = 0
        #: Weight generation pinned at ADMISSION (None until then):
        #: the request prefils and decodes on this generation to
        #: completion even if update_weights lands mid-stream.
        self.gen: Optional[int] = None


class TokenStream:
    """Consumer side of one request: iterate token ids as they are
    sampled. Ends at EOS/budget/cancel; raises if the engine failed
    the request. `finish_reason` is set once exhausted."""

    def __init__(self, engine: "InferenceEngine", req: _Request):
        self._engine = engine
        self._req = req
        self.finish_reason: Optional[str] = None

    @property
    def request_id(self) -> str:
        return self._req.request_id

    def __iter__(self) -> Iterator[int]:
        return self

    def __next__(self) -> int:
        if self.finish_reason is not None:
            raise StopIteration
        while True:
            try:
                kind, value = self._req.out.get(timeout=1.0)
                break
            except queue.Empty:
                # Belt-and-braces: a dead engine fails every request
                # with a sentinel, but if this request somehow missed
                # one the consumer must raise, not hang forever.
                if (
                    self._engine._dead is not None
                    and self._req.out.empty()
                ):
                    self.finish_reason = "error"
                    raise EngineDead(
                        "engine died mid-stream"
                    ) from self._engine._dead
        if kind == "tok":
            return value
        if kind == "end":
            self.finish_reason = value
            raise StopIteration
        self.finish_reason = "error"
        raise value

    def cancel(self) -> None:
        self._engine.cancel(self._req.request_id)


class BatchProgram:
    """Pluggable batch-program hook for the engine's policy path.

    A program turns one PADDED row batch of inputs into a dict of
    per-row output arrays with ONE (jitted) call; the engine's step
    loop owns batching — it coalesces every pending `submit_policy`
    request into the smallest bucket that fits and scatters the
    output rows back to their tickets. Subclasses (e.g.
    rl.dataflow.PolicyProgram) override `run`; `buckets` is the
    ascending set of padded batch sizes (the compile-once shape set,
    exactly like the prefill length buckets on the LLM path).
    """

    #: Ascending padded batch sizes; a single submit may not exceed
    #: buckets[-1] rows.
    buckets: tuple = (8, 16, 32, 64, 128, 256)

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def run(self, params, inputs, key) -> Dict[str, Any]:
        """(params, padded inputs [bucket, ...], PRNG key) -> dict of
        [bucket, ...] output arrays. Must be shape-stable per bucket
        (jit compiles once per bucket)."""
        raise NotImplementedError


class _PolicyRequest:
    __slots__ = (
        "inputs", "n", "done", "result", "error", "version",
        "submitted_ts",
    )

    def __init__(self, inputs: np.ndarray):
        self.inputs = inputs
        self.n = int(len(inputs))
        self.done = threading.Event()
        self.result: Optional[Dict[str, np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.version: Optional[int] = None
        self.submitted_ts = time.perf_counter()


class PolicyTicket:
    """Consumer side of one policy-path request: `result()` blocks
    until the engine's step loop has served the rows (raising, never
    hanging, if the engine dies first). `version` is the weight
    version the reply was computed with — the staleness signal the
    RL dataflow's `max_weight_lag` throttle reads."""

    def __init__(self, engine: "InferenceEngine", req: _PolicyRequest):
        self._engine = engine
        self._req = req

    @property
    def version(self) -> Optional[int]:
        return self._req.version

    def result(
        self, timeout: Optional[float] = None
    ) -> Dict[str, np.ndarray]:
        deadline = (
            None if timeout is None else time.perf_counter() + timeout
        )
        while True:
            wait = 1.0
            if deadline is not None:
                wait = min(wait, deadline - time.perf_counter())
                if wait <= 0:
                    raise TimeoutError(
                        "policy request not served in time"
                    )
            if self._req.done.wait(wait):
                break
            # Belt-and-braces (same contract as TokenStream): a dead
            # engine fails every ticket, but if this one somehow
            # missed the sentinel the consumer must raise, not hang.
            if (
                self._engine._dead is not None
                and not self._req.done.is_set()
            ):
                raise EngineDead(
                    "engine died with policy request pending"
                ) from self._engine._dead
        if self._req.error is not None:
            raise self._req.error
        assert self._req.result is not None
        return self._req.result


class InferenceEngine:
    def __init__(
        self,
        params: Dict[str, Any],
        cfg,
        engine_config: Optional[EngineConfig] = None,
        *,
        family: str = "",
        app: str = "",
        deployment: str = "",
        program: Optional[BatchProgram] = None,
    ):
        import jax

        ec = engine_config or EngineConfig()
        self.params = params
        self.cfg = cfg
        self.config = ec
        self.family = family
        self._program = program
        if cfg is None and program is None:
            raise ValueError(
                "cfg=None (policy-only engine) requires a `program`"
            )
        self._tags = {
            "app": app, "deployment": deployment,
            "family": family or "default",
        }
        self._lock = threading.Lock()
        self._wake = threading.Event()
        # Versioned weight generations (drainless sync): generation
        # index -> {version, params, refs}. `refs` counts the LLM
        # requests pinned at admission; a non-latest generation is
        # dropped the moment its count returns to zero. The policy
        # path always reads the latest generation and pins nothing
        # (one batch = one forward, no stream to keep token-exact).
        self._gens: Dict[int, Dict[str, Any]] = {
            0: {"version": 0, "params": params, "refs": 0}
        }
        self._gen_latest = 0
        self._weight_version = 0
        if cfg is not None:
            block_len = ec.kv_block_len or default_block_len(
                ec.prefill_chunk
            )
            n_blocks = ec.kv_blocks or (
                ec.slots * (ec.max_len // block_len) + 1
            )
            self._kv = PagedKVCache(
                cfg, n_blocks, block_len, ec.max_len, ec.prefill_chunk
            )
            self._sched = SlotScheduler(ec.slots, ec.max_waiting)
        else:
            self._kv = None
            self._sched = None
        # Per-slot decode state. positions/alive/tables live host-side
        # (the engine mutates them per admission/step); last_logits
        # stays on device.
        import jax.numpy as jnp

        if cfg is not None:
            self._positions = np.zeros(ec.slots, np.int32)
            self._alive = np.zeros(ec.slots, bool)
            self._tables = np.full(
                (ec.slots, self._kv.max_blocks), NULL_BLOCK, np.int32
            )
            self._last_logits = jnp.zeros(
                (ec.slots, cfg.vocab_size), jnp.float32
            )
        self._base_key = jax.random.PRNGKey(ec.seed)
        # Compile-watch registration (ISSUE 15 satellite): the
        # engine's jitted entry points are named programs, so "the
        # engine compiles ONCE per geometry" (PR 11) is a tested
        # counter instead of a comment — a mid-traffic recompile is
        # an engine bug, and now it is a visible one (engine_stats /
        # /api/serve / verdict.compile). Family rides in the program
        # NAME (bounded: model families), never a free-form label.
        from .._private import compile_watch

        fam = family or "default"
        if cfg is not None:
            from ..models.generate import (
                paged_decode_step,
                paged_prefill,
            )

            self._paged_prefill = compile_watch.instrument(
                f"engine.paged_prefill[{fam}]", paged_prefill
            )
            self._paged_decode = compile_watch.instrument(
                f"engine.paged_decode_step[{fam}]", paged_decode_step
            )
        if program is not None:
            # Late-bound through self._program so a swapped/patched
            # program (tests, hot program replacement) takes effect —
            # the watcher wraps the CALL, not one captured function.
            self._program_run = compile_watch.instrument(
                f"engine.policy[{fam}]",
                lambda *a, **k: self._program.run(*a, **k),
            )
        self._prefilling: Optional[_Request] = None
        self._by_id: Dict[str, _Request] = {}
        self._policy_pending: "deque[_PolicyRequest]" = deque()
        self._policy_rows_pending = 0
        self._policy_steps = 0
        self._policy_rows_served = 0
        self._steps = 0
        self._tokens_emitted = 0
        self._requests_done = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_tokens_saved = 0
        self._dead: Optional[BaseException] = None
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"llm-engine:{family or 'default'}",
        )
        self._thread.start()

    # -- public --------------------------------------------------------
    def submit(
        self,
        prompt: List[int],
        *,
        max_new_tokens: Optional[int] = None,
        eos_token: Optional[int] = None,
        request_id: Optional[str] = None,
    ) -> TokenStream:
        ec = self.config
        if self._kv is None:
            raise ValueError(
                "policy-only engine (cfg=None) has no LLM path; use "
                "submit_policy()"
            )
        max_new = int(
            ec.max_new_tokens if max_new_tokens is None
            else max_new_tokens
        )
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        prompt = [int(t) for t in prompt]
        bucket = self._kv.bucket_for(len(prompt))
        if len(prompt) + max_new > ec.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds per-request capacity max_len={ec.max_len}"
            )
        if eos_token is not None and eos_token != int(eos_token):
            raise ValueError(
                f"eos_token must be integral, got {eos_token!r}"
            )
        total_blocks = self._kv.blocks_for(
            max(bucket, len(prompt) + max_new)
        )
        if total_blocks > self._kv.alloc.capacity():
            # OOM is a SHED, not a crash or an unserviceable queue
            # entry: this request could never be admitted.
            raise EngineOverloaded(
                f"request needs {total_blocks} KV blocks but the pool "
                f"holds {self._kv.alloc.capacity()}; shed"
            )
        req = _Request(
            request_id or uuid.uuid4().hex[:16],
            prompt,
            max_new,
            ec.eos_token if eos_token is None else int(eos_token),
        )
        req.bucket = bucket
        req.total_blocks = total_blocks
        if ec.prefix_cache:
            req.prefix_keys = self._kv.prefix_keys(prompt)
        with self._lock:
            if self._dead is not None or self._stopping:
                raise EngineDead(
                    "engine is shut down"
                ) from self._dead
            if req.request_id in self._by_id:
                raise ValueError(
                    f"duplicate request_id {req.request_id!r}"
                )
            self._sched.submit(req)
            self._by_id[req.request_id] = req
        self._wake.set()
        return TokenStream(self, req)

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued or in-flight request. Queued requests end
        immediately; running ones are reaped (slot + blocks freed) at
        the top of the next engine iteration — mid-decode, not at
        stream end."""
        with self._lock:
            req = self._by_id.get(request_id)
            if req is None:
                return False
            req.cancelled.set()
            if self._sched.remove_waiting(req):
                self._finish_locked(req, "cancelled")
        self._wake.set()
        return True

    def update_weights(
        self, params: Dict[str, Any], *, version: Optional[int] = None
    ) -> int:
        """Install a new weight generation WITHOUT draining the
        engine (ISSUE 13 tentpole): in-flight LLM requests keep the
        generation they were admitted under and finish token-exact on
        it; the next admission — and the next policy batch — serves
        the new weights. Returns the installed weight version
        (monotonic; pass `version` to carry the learner's own
        numbering onto /metrics)."""
        if version is not None and version != int(version):
            raise ValueError(
                f"version must be integral, got {version!r}"
            )
        with self._lock:
            if self._dead is not None or self._stopping:
                raise EngineDead(
                    "engine is shut down"
                ) from self._dead
            v = (
                int(version) if version is not None
                else self._weight_version + 1
            )
            if v <= self._weight_version:
                raise ValueError(
                    f"weight version must increase: got {v}, "
                    f"serving {self._weight_version}"
                )
            self._gen_latest += 1
            self._gens[self._gen_latest] = {
                "version": v, "params": params, "refs": 0,
            }
            self._weight_version = v
            self.params = params
            self._prune_gens_locked()
        self._observe_weights()
        self._wake.set()
        return v

    def _prune_gens_locked(self) -> None:
        for gen in [
            g for g, e in self._gens.items()
            if g != self._gen_latest and e["refs"] <= 0
        ]:
            del self._gens[gen]

    def submit_policy(self, inputs) -> PolicyTicket:
        """Queue one row batch for the policy batch program; the step
        loop coalesces everything pending into one padded bucket and
        runs the program's jitted forward once. Ragged per-env
        requests from many callers batch exactly like ragged chat
        traffic on the LLM path."""
        if self._program is None:
            raise ValueError(
                "engine was built without a policy batch program"
            )
        inputs = np.asarray(inputs)
        if inputs.ndim < 1 or len(inputs) < 1:
            raise ValueError("submit_policy needs >= 1 input row")
        if len(inputs) > self._program.buckets[-1]:
            raise ValueError(
                f"policy batch of {len(inputs)} rows exceeds the "
                f"program's largest bucket "
                f"{self._program.buckets[-1]}; split it"
            )
        req = _PolicyRequest(inputs)
        with self._lock:
            if self._dead is not None or self._stopping:
                raise EngineDead(
                    "engine is shut down"
                ) from self._dead
            if (
                self._policy_rows_pending + req.n
                > self.config.max_policy_rows
            ):
                raise EngineOverloaded(
                    f"policy backlog full "
                    f"({self.config.max_policy_rows} rows); shed"
                )
            self._policy_pending.append(req)
            self._policy_rows_pending += req.n
        self._wake.set()
        return PolicyTicket(self, req)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = (
                self._sched.stats() if self._sched is not None
                else {"slots_total": 0, "slots_used": 0, "waiting": 0}
            )
            out.update(
                family=self.family,
                steps=self._steps,
                tokens_emitted=self._tokens_emitted,
                requests_done=self._requests_done,
                prefilling=self._prefilling is not None,
                prefix_hits=self._prefix_hits,
                prefix_misses=self._prefix_misses,
                prefix_tokens_saved=self._prefix_tokens_saved,
                weight_version=self._weight_version,
                weight_gens=len(self._gens),
                policy_pending_rows=self._policy_rows_pending,
                policy_steps=self._policy_steps,
                policy_rows_served=self._policy_rows_served,
                dead=self._dead is not None,
            )
            # Per-family compile counts (compile-watch): prefill /
            # decode / policy programs, each {compiles,
            # distinct_shapes}. Steady state after warmup is a FIXED
            # number — movement under traffic is a recompile bug.
            compiles: Dict[str, Any] = {}
            if self._kv is not None:
                compiles["prefill"] = self._paged_prefill.stats()
                compiles["decode"] = self._paged_decode.stats()
            if self._program is not None:
                compiles["policy"] = self._program_run.stats()
            if compiles:
                out["compiles"] = compiles
            if self._kv is not None:
                out.update(
                    kv_bytes=self._kv.nbytes(),
                    kv_block_len=self._kv.block_len,
                    **self._kv.alloc.stats(),
                )
        return out

    def close(self) -> None:
        """Stop the loop and fail everything in flight (the multiplex
        LRU calls this on eviction). In-flight consumers get an ERROR,
        not a clean end — a truncated response must be detectable."""
        with self._lock:
            self._stopping = True
        self._wake.set()
        self._thread.join(timeout=30)
        with self._lock:
            if self._dead is None:
                self._dead = EngineDead("engine unloaded")
            self._fail_all_locked(
                EngineDead("engine unloaded with request in flight")
            )

    # Multiplex eviction hook (serve/multiplex.py looks for it).
    __serve_unload__ = close

    # -- engine loop ---------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    if self._stopping:
                        return
                did_work = self._step()
                if not did_work:
                    self._wake.wait(self.config.idle_wait_s)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — forwarded to
            # every consumer; the loop must never die silently.
            failure = EngineDead(f"engine loop died: {e!r}")
            failure.__cause__ = e
            with self._lock:
                self._dead = e
                self._fail_all_locked(failure)

    def _step(self) -> bool:
        """One engine iteration; returns whether any work happened.
        Policy batches go first: their callers are blocked env-runner
        threads, and one batched forward is cheap next to a decode
        step over the full slot batch."""
        worked = self._reap_cancelled()
        worked = self._policy_step() or worked
        if self._sched is not None:
            worked = self._advance_prefill() or worked
            worked = self._decode() or worked
        return worked

    # -- policy path ---------------------------------------------------
    def _policy_step(self) -> bool:
        """Serve every pending policy request that fits the largest
        bucket in ONE padded batched forward on the LATEST weight
        generation; scatter output rows back to their tickets."""
        if self._program is None:
            return False
        with self._lock:
            if not self._policy_pending:
                return False
            cap = self._program.buckets[-1]
            batch: List[_PolicyRequest] = []
            rows = 0
            while (
                self._policy_pending
                and rows + self._policy_pending[0].n <= cap
            ):
                req = self._policy_pending.popleft()
                self._policy_rows_pending -= req.n
                batch.append(req)
                rows += req.n
            entry = self._gens[self._gen_latest]
            params, version = entry["params"], entry["version"]
        import jax

        t0 = time.perf_counter()
        bucket = self._program.bucket_for(rows)
        sample = batch[0].inputs
        padded = np.zeros(
            (bucket, *sample.shape[1:]), dtype=sample.dtype
        )
        cursor = 0
        for req in batch:
            padded[cursor:cursor + req.n] = req.inputs
            cursor += req.n
        key = jax.random.fold_in(
            jax.random.fold_in(self._base_key, 0x9E37),
            self._policy_steps,
        )
        try:
            outs = self._program_run(params, padded, key)
            host = {k: np.asarray(v) for k, v in outs.items()}
        except BaseException as e:
            # A program failure fails THIS batch's tickets (the
            # callers must not hang) and then the loop: a broken
            # program cannot serve the next batch either.
            for req in batch:
                req.error = EngineDead(
                    f"policy batch program failed: {e!r}"
                )
                req.error.__cause__ = e
                req.done.set()
            raise
        cursor = 0
        for req in batch:
            req.result = {
                k: v[cursor:cursor + req.n] for k, v in host.items()
            }
            req.version = version
            req.done.set()
            cursor += req.n
        self._policy_steps += 1
        self._policy_rows_served += rows
        self._observe_policy(
            (time.perf_counter() - t0) * 1e3, rows, bucket
        )
        return True

    # -- cancellation / completion ------------------------------------
    def _reap_cancelled(self) -> bool:
        if self._sched is None:
            return False
        worked = False
        with self._lock:
            # The prefilling request is ALSO in sched.running (its
            # slot was claimed at admission) — release it through this
            # branch first so the loop below can't double-release the
            # slot (release() on an already-freed slot raises and
            # would kill the whole loop).
            if (
                self._prefilling is not None
                and self._prefilling.cancelled.is_set()
            ):
                req = self._prefilling
                self._prefilling = None
                self._release_locked(req.slot, req, "cancelled")
                worked = True
            for slot, req in list(self._sched.running.items()):
                if req.cancelled.is_set():
                    self._release_locked(slot, req, "cancelled")
                    worked = True
        return worked

    def _release_locked(
        self, slot: int, req: _Request, reason: str
    ) -> None:
        self._sched.release(slot)
        self._alive[slot] = False
        self._tables[slot, :] = NULL_BLOCK
        if req.block_ids:
            # Unpin: full prompt blocks stay in the prefix cache
            # (refcount 0, LRU-evictable); private blocks go back to
            # the free list. block_ids cleared so no path can double-
            # free (the allocator would raise and kill the loop).
            self._kv.alloc.release(req.block_ids)
            req.block_ids = []
        self._unpin_gen_locked(req)
        self._finish_locked(req, reason)

    def _unpin_gen_locked(self, req: _Request) -> None:
        if req.gen is None:
            return
        entry = self._gens.get(req.gen)
        req.gen = None
        if entry is not None:
            entry["refs"] -= 1
            self._prune_gens_locked()

    def _finish_locked(self, req: _Request, reason: str) -> None:
        self._by_id.pop(req.request_id, None)
        self._requests_done += 1
        req.out.put(("end", reason))
        self._observe_finish(reason)
        # Push occupancy from the retirement itself: cancellation/
        # drain may leave no alive rows, so no decode step would ever
        # publish the freed slots (the gauge throttle keeps this
        # cheap; a slots_used zero-crossing always goes out).
        self._observe_occupancy()

    def _fail_all_locked(self, error: BaseException) -> None:
        if self._prefilling is not None:
            doomed = [self._prefilling]
            self._prefilling = None
        else:
            doomed = []
        if self._sched is not None:
            doomed.extend(self._sched.drain())
            self._alive[:] = False
            self._tables[:, :] = NULL_BLOCK
        for req in doomed:
            if req.block_ids:
                try:
                    self._kv.alloc.release(req.block_ids)
                except Exception:
                    pass  # dying anyway; never mask the real failure
                req.block_ids = []
            req.gen = None
            self._by_id.pop(req.request_id, None)
            req.out.put(("err", error))
        # Pending policy tickets fail FAST too: their callers are
        # synchronously blocked env-runner threads — an engine death
        # must turn into EngineDead there, never a hang.
        while self._policy_pending:
            preq = self._policy_pending.popleft()
            self._policy_rows_pending -= preq.n
            preq.error = error
            preq.done.set()
        self._observe_occupancy()

    # -- admission / block allocation ---------------------------------
    def _skip_for(self, req: _Request, hit_blocks: int) -> int:
        """Prefill tokens a prefix hit lets this request skip: capped
        at len(prompt) - 1 (the LAST prompt token is always computed —
        its logits seed decoding) and rounded down to a whole prefill
        chunk (offsets stay chunk-aligned, keeping the chunk shape
        static)."""
        bl = self._kv.block_len
        chunk = self._kv.prefill_chunk
        usable = min(hit_blocks * bl, len(req.prompt) - 1)
        return (usable // chunk) * chunk

    def _gate_locked(self, req: _Request) -> bool:
        """Admission gate: can the FIFO head get its blocks NOW? The
        reservation needs `total - skip` fresh blocks, and pinning the
        hit additionally consumes `cached` availability — only the
        hit blocks that are currently refcount-0 (cached-free) leave
        `available()` when pinned; hits already pinned by a live
        request are free to share. A gated admission can therefore
        never fail its reservation one line later, and sharing a
        LIVE request's prefix genuinely relaxes admission."""
        alloc = self._kv.alloc
        hits = alloc.peek_prefix(req.prefix_keys)
        skip_blocks = self._skip_for(req, hits) // self._kv.block_len
        cached = alloc.peek_cached(req.prefix_keys, skip_blocks)
        return (
            alloc.available() - cached
            >= req.total_blocks - skip_blocks
        )

    def _allocate_locked(self, req: _Request) -> None:
        """Pin the request's prefix-cache hit (if any) and reserve the
        rest of its pages; build its table row. Runs under the lock in
        the same critical section as the gate."""
        alloc = self._kv.alloc
        shared = alloc.match_prefix(req.prefix_keys)
        skip = self._skip_for(req, len(shared))
        skip_blocks = skip // self._kv.block_len
        if len(shared) > skip_blocks:
            # Hit blocks beyond the chunk-aligned usable window: unpin
            # them again (they stay cached).
            alloc.release(shared[skip_blocks:])
            shared = shared[:skip_blocks]
        req.skip = skip
        req.offset = skip
        req.n_shared = skip_blocks
        req.block_ids = shared + alloc.reserve(
            req.total_blocks - skip_blocks
        )
        row = self._tables[req.slot]
        row[:] = NULL_BLOCK
        row[: len(req.block_ids)] = req.block_ids
        if skip:
            self._prefix_hits += 1
            self._prefix_tokens_saved += skip
        else:
            self._prefix_misses += 1
        self._observe_prefix(skip)

    # -- prefill -------------------------------------------------------
    def _advance_prefill(self) -> bool:
        """Admit (if idle) and advance the current prefill by ONE
        chunk, written straight into the request's pages. Returns
        whether prefill work happened."""
        import jax.numpy as jnp

        with self._lock:
            req = self._prefilling
            if req is None:
                admitted = self._sched.admit_next(
                    gate=self._gate_locked
                )
                if admitted is None:
                    return False
                req, slot = admitted
                req.slot = slot
                # Pin the weight generation at ADMISSION: everything
                # this request computes — every prefill chunk and
                # every decode step — uses these params, even if a
                # weight push lands mid-stream (drainless sync's
                # token-exactness contract).
                req.gen = self._gen_latest
                self._gens[req.gen]["refs"] += 1
                self._allocate_locked(req)
                self._prefilling = req
        if req.padded is None:
            padded = np.zeros((1, req.bucket), np.int32)
            padded[0, : len(req.prompt)] = req.prompt
            req.padded = padded
        chunk = self.config.prefill_chunk
        t0 = time.perf_counter()
        tokens = jnp.asarray(req.padded[:, req.offset:req.offset + chunk])
        table = jnp.asarray(self._tables[req.slot:req.slot + 1])
        logits, pool = self._paged_prefill(
            self._gens[req.gen]["params"],
            self.cfg,
            tokens,
            self._kv.pool,
            table,
            jnp.int32(req.offset),
            jnp.int32(req.offset + chunk),
        )
        self._kv.pool = pool
        req.offset += chunk
        last_chunk = req.offset >= req.bucket
        if last_chunk:
            # Next-token logits come from the prompt's LAST REAL
            # position (inside this chunk by bucket construction:
            # the final chunk covers [bucket - chunk, bucket) and
            # len(prompt) > bucket - chunk — prefix skip never
            # reaches the final chunk, it is capped at
            # len(prompt) - 1).
            local = len(req.prompt) - 1 - (req.offset - chunk)
            last_row = logits[0, local]
            self._last_logits = self._last_logits.at[req.slot].set(
                last_row
            )
            last_row.block_until_ready()
        else:
            logits.block_until_ready()
        self._observe_prefill(
            (time.perf_counter() - t0) * 1e3, chunk
        )
        if last_chunk:
            req.padded = None
            with self._lock:
                self._prefilling = None
                # Cancelled during the final chunk: reap now rather
                # than decoding a dead row for one step.
                if req.cancelled.is_set():
                    self._release_locked(req.slot, req, "cancelled")
                    return True
                if self.config.prefix_cache:
                    # Publish the full prompt blocks this request
                    # computed (not the ones it shared) for future
                    # prefix hits; first writer wins on races.
                    for i in range(
                        req.n_shared, len(req.prefix_keys)
                    ):
                        self._kv.alloc.register(
                            req.block_ids[i], req.prefix_keys[i]
                        )
                self._positions[req.slot] = len(req.prompt)
                self._alive[req.slot] = True
        return True

    # -- decode --------------------------------------------------------
    def _decode(self) -> bool:
        import jax
        import jax.numpy as jnp

        alive_idx = np.flatnonzero(self._alive)
        if alive_idx.size == 0:
            return False
        batch = int(alive_idx.size)
        ec = self.config
        t0 = time.perf_counter()
        key = jax.random.fold_in(self._base_key, self._steps)
        # Partition the alive batch by pinned weight generation. In
        # steady state there is exactly one group and this is the
        # PR 11 fast path verbatim; in the transient window after an
        # update_weights there are two (old streams finishing, new
        # admissions starting) and each runs its own masked decode
        # step over the SAME pool — masks are disjoint and dead rows
        # scatter to the null block, so the groups can't cross-talk.
        with self._lock:
            by_gen: Dict[int, List[int]] = {}
            for slot in alive_idx:
                req = self._sched.running.get(int(slot))
                if req is None:
                    continue
                by_gen.setdefault(
                    req.gen if req.gen is not None else 0, []
                ).append(int(slot))
        if not by_gen:
            return False
        tables = jnp.asarray(self._tables)
        positions = jnp.asarray(self._positions)
        if len(by_gen) == 1:
            gen = next(iter(by_gen))
            token, pool, last_logits = self._paged_decode(
                self._gens[gen]["params"],
                self.cfg,
                self._kv.pool,
                tables,
                self._last_logits,
                positions,
                jnp.asarray(self._alive),
                key,
                temperature=ec.temperature,
                top_k=ec.top_k,
            )
            self._kv.pool = pool
            self._last_logits = last_logits
            tokens = np.asarray(token)  # device->host sync per step
        else:
            # Mixed-generation window: paged_decode_step donates
            # last_logits on accelerator backends, so each group gets
            # a PRIVATE copy of the pre-step logits (`+ 0` forces a
            # fresh buffer) and the surviving rows merge back — a
            # group must never read another group's freshly-written
            # junk rows, and the donated original must never be
            # reused.
            base_logits = self._last_logits
            merged = base_logits
            pool = self._kv.pool
            # Tokens merge on-device too: a per-group np.asarray here
            # would block the host once per generation inside the hot
            # step loop (static analyzer rule RT303); one sync after
            # the loop costs the same D2H as the single-gen path.
            merged_tokens = None
            for gen in sorted(by_gen):
                mask = np.zeros(ec.slots, bool)
                mask[by_gen[gen]] = True
                gmask = jnp.asarray(mask)
                token, pool, out_logits = self._paged_decode(
                    self._gens[gen]["params"],
                    self.cfg,
                    pool,
                    tables,
                    base_logits + 0,
                    positions,
                    gmask,
                    key,
                    temperature=ec.temperature,
                    top_k=ec.top_k,
                )
                merged = jnp.where(
                    gmask[:, None], out_logits, merged
                )
                merged_tokens = jnp.where(
                    gmask,
                    token,
                    0 if merged_tokens is None else merged_tokens,
                )
            self._kv.pool = pool
            self._last_logits = merged
            tokens = np.asarray(merged_tokens)  # ONE sync for the window
        step_ms = (time.perf_counter() - t0) * 1e3
        self._steps += 1
        now = time.perf_counter()
        emitted = 0
        with self._lock:
            for slot in alive_idx:
                req = self._sched.running.get(int(slot))
                if req is None:  # freed this iteration
                    continue
                tok = int(tokens[slot])
                if req.first_token_ts is None:
                    req.first_token_ts = now
                    self._observe_ttft(
                        (now - req.submitted_ts) * 1e3
                    )
                req.out.put(("tok", tok))
                req.emitted += 1
                emitted += 1
                self._positions[slot] += 1
                if tok == req.eos_token:
                    self._release_locked(int(slot), req, "stop")
                elif req.emitted >= req.max_new_tokens:
                    self._release_locked(int(slot), req, "length")
            self._tokens_emitted += emitted
        self._observe_step(step_ms, batch, emitted)
        return True

    # -- metrics -------------------------------------------------------
    # All hooks are guarded no-ops on failure: observability must
    # never fail a decode (serve/observability.py owns the metric
    # definitions; the engine just reports).

    def _block_stats(self) -> Dict[str, int]:
        if self._kv is None:
            return {"kv_used": 0, "kv_total": 0, "kv_cached": 0}
        alloc = self._kv.alloc
        return {
            "kv_used": alloc.used(),
            "kv_total": alloc.capacity(),
            "kv_cached": alloc.cached(),
        }

    def _observe_step(
        self, step_ms: float, batch: int, tokens: int
    ) -> None:
        try:
            from ..serve.observability import observe_engine_step

            stats = self._sched.stats()
            observe_engine_step(
                self._tags, step_ms, batch, tokens,
                stats["slots_used"], stats["slots_total"],
                stats["waiting"], **self._block_stats(),
            )
        except Exception:
            pass

    def _observe_prefill(self, chunk_ms: float, tokens: int) -> None:
        try:
            from ..serve.observability import observe_engine_prefill

            observe_engine_prefill(self._tags, chunk_ms, tokens)
        except Exception:
            pass

    def _observe_prefix(self, skip_tokens: int) -> None:
        try:
            from ..serve.observability import observe_engine_prefix

            observe_engine_prefix(self._tags, skip_tokens)
        except Exception:
            pass

    def _observe_ttft(self, ttft_ms: float) -> None:
        try:
            from ..serve.observability import observe_engine_ttft

            observe_engine_ttft(self._tags, ttft_ms)
        except Exception:
            pass

    def _observe_finish(self, reason: str) -> None:
        try:
            from ..serve.observability import observe_engine_finish

            observe_engine_finish(self._tags, reason)
        except Exception:
            pass

    def _observe_occupancy(self) -> None:
        try:
            from ..serve.observability import (
                observe_engine_occupancy,
            )

            if self._sched is None:
                return
            stats = self._sched.stats()
            observe_engine_occupancy(
                self._tags, stats["slots_used"],
                stats["slots_total"], stats["waiting"],
                **self._block_stats(),
            )
        except Exception:
            pass

    def _observe_weights(self) -> None:
        try:
            from ..serve.observability import observe_engine_weights

            observe_engine_weights(self._tags, self._weight_version)
        except Exception:
            pass

    def _observe_policy(
        self, batch_ms: float, rows: int, bucket: int
    ) -> None:
        try:
            from ..serve.observability import observe_engine_policy

            observe_engine_policy(self._tags, batch_ms, rows, bucket)
        except Exception:
            pass
